"""kvlens tests (ISSUE 18): the memory-economy observatory.

The acceptance contract this module pins: the SHARDS-sampled
reuse-distance tracker's miss-ratio curve matches the exact LRU golden
at rate=1 (every access sampled — stack distances are exact), the
hash sampler is bit-deterministic per seed, the thrash detector bills
evict→refetch churn in re-prefill chunk-seconds on an injected clock
(inside the window only, adopted refetches pay the wire again), the
obs gate makes every producer a no-op when off, /kvz serves JSON and
Prometheus text, the `python -m dnn_tpu.obs kvlens` CLI smoke passes —
and one real in-process ContinuousBatcher under a forced-eviction
working set feeds the lens from the actual radix-store seams (access/
insert/evict/refetch with cause attribution), with the curve axis
pinned to the EFFECTIVE pool (the allocator bound, not the nominal
prefix_cache knob) so the multipliers never mis-scale."""

import json
import subprocess
import sys
import urllib.request
from types import SimpleNamespace

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.obs.kvlens import DEFAULT_MULTS, KVLens

BP = 4  # tiny block_len for the unit legs: 1 chunk = 4 tokens


def _blk(base):
    """One full chunk of distinct tokens starting at `base`."""
    return np.arange(base, base + BP)


@pytest.fixture(autouse=True)
def _obs_on():
    """Producers self-gate; unit legs run with the gate ON and restore."""
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


# ----------------------------------------------------------------------
# miss-ratio curve: exact LRU golden + sampling determinism
# ----------------------------------------------------------------------

def test_mrc_golden_exact_lru():
    # pool=4 ⇒ hypothetical caps (2, 4, 8, 16, 32); rate=1 makes the
    # sampled stack the exact LRU stack. Trace A B C A: the re-accessed
    # A sits at stack distance 2 (B, C more recent) — a hit at every
    # capacity > 2, a miss at the 0.5x (=2-block) pool
    lens = KVLens(4, BP, seed=0, rate=1.0, now=lambda: 0.0)
    for p in (_blk(0), _blk(100), _blk(200), _blk(0)):
        lens.on_access(p, n_resident=0)
    got = [c["predicted_hit_ratio"] for c in lens.curve()]
    assert got == [0.0, 0.25, 0.25, 0.25, 0.25], got
    assert lens.sampled == 4 and lens.sampled_cold == 3
    assert [c["capacity_blocks"] for c in lens.curve()] == [2, 4, 8, 16, 32]
    # per-mult reader agrees with the curve rows
    assert lens.predicted_hit_ratio(0.5) == 0.0
    assert lens.predicted_hit_ratio(2.0) == 0.25


def test_curve_is_monotone_nondecreasing():
    # structural: a re-access that fits under cap_i fits under every
    # larger cap, so the curve can never dip as capacity grows
    lens = KVLens(8, BP, seed=3, rate=1.0, now=lambda: 0.0)
    for i in range(300):
        lens.on_access(_blk((i % 23) * 1000))
    vals = [c["predicted_hit_ratio"] for c in lens.curve()]
    assert all(a <= b for a, b in zip(vals, vals[1:])), vals


def test_sampling_is_deterministic_per_seed():
    def run(seed):
        lens = KVLens(8, BP, seed=seed, rate=0.3, now=lambda: 0.0)
        for i in range(200):
            lens.on_access(_blk((i % 17) * 1000))
        return lens

    a, b = run(7), run(7)
    assert a.curve() == b.curve() and a.sampled == b.sampled
    assert 0 < a.sampled < a.accesses  # the rate really subsamples
    # a different seed picks a different deterministic slice of keys
    c = run(8)
    assert (c.sampled, c.curve()) != (a.sampled, a.curve())


def test_measured_tally_anchors_the_prediction():
    lens = KVLens(4, BP, seed=0, rate=1.0, now=lambda: 0.0)
    assert lens.measured_hit_ratio() is None  # no accesses yet
    lens.on_access(np.concatenate([_blk(0), _blk(100)]), n_resident=1)
    lens.on_access(_blk(0), n_resident=1)
    assert lens.measured_accesses == 3 and lens.measured_hits == 2
    assert lens.measured_hit_ratio() == pytest.approx(2 / 3)
    # n_resident is clamped to the chunks actually presented
    lens.on_access(_blk(200), n_resident=99)
    assert lens.measured_hits == 3


# ----------------------------------------------------------------------
# thrash detector + forensics ledger (injected clock)
# ----------------------------------------------------------------------

def test_thrash_window_arithmetic():
    t = [0.0]
    lens = KVLens(4, BP, seed=0, rate=1.0, thrash_window_s=10.0,
                  bytes_per_block=64, now=lambda: t[0])
    lens.note_prefill(2, 1.0)  # EMA seeds at 0.5 s/chunk
    node = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(_blk(0), [node])
    assert node.obskey is not None  # the stamp evict reads back
    lens.on_evict([node.obskey], cause="capacity")
    t[0] = 5.0  # inside the window: a refetch, billed at the EMA price
    lens.on_insert(_blk(0), [SimpleNamespace(depth=1, obskey=None)])
    assert lens.refetch_blocks == 1
    assert lens.thrash_chunk_seconds == pytest.approx(0.5)
    # outside the window: churn, not thrash
    nb = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(_blk(100), [nb])
    lens.on_evict([nb.obskey], cause="capacity")
    t[0] = 16.0
    lens.on_insert(_blk(100), [SimpleNamespace(depth=1, obskey=None)])
    assert lens.refetch_blocks == 1
    # an ADOPTED refetch pays the wire again
    na = SimpleNamespace(depth=1, obskey=None)
    lens.on_insert(_blk(200), [na], origin="adopted")
    lens.on_evict([na.obskey], cause="capacity")
    t[0] = 17.0
    lens.on_insert(_blk(200), [SimpleNamespace(depth=1, obskey=None)],
                   origin="adopted")
    assert lens.refetch_blocks == 2
    assert lens.thrash_migrated_bytes == 64
    kinds = [e["kind"] for e in lens.ledger.events()]
    assert kinds.count("refetch") == 2 and "birth" in kinds


def test_eviction_cause_labels():
    lens = KVLens(4, BP, seed=0, rate=1.0, now=lambda: 0.0)
    lens.on_evict([b"k" * 16, b"l" * 16], cause="capacity")
    lens.on_evict([b"m" * 16], cause="lease_expiry")
    lens.on_evict([None], cause="shutdown")  # pre-lens node: cause holds
    assert lens.evictions_by_cause == {
        "capacity": 2, "lease_expiry": 1, "shutdown": 1}
    prom = lens.render_prom()
    assert 'dnn_tpu_kvlens_evictions_total{cause="capacity"} 2' in prom
    assert 'dnn_tpu_kvlens_evictions_total{cause="lease_expiry"} 1' in prom


def test_gate_off_records_nothing():
    obs.set_enabled(False)
    lens = KVLens(4, BP, seed=0, rate=1.0)
    lens.on_access(_blk(0), n_resident=1)
    lens.on_insert(_blk(0), [SimpleNamespace(depth=1, obskey=None)])
    lens.on_evict([b"x" * 16])
    lens.on_share(3)
    lens.on_migrate(2, 128)
    lens.note_prefill(1, 1.0)
    assert lens.accesses == 0 and lens.births == 0 and lens.shares == 0
    assert lens.evictions_by_cause == {} and len(lens.ledger) == 0
    assert lens.measured_hit_ratio() is None


# ----------------------------------------------------------------------
# /kvz endpoint + CLI
# ----------------------------------------------------------------------

def test_kvz_endpoint_json_and_prom():
    lens = KVLens(4, BP, seed=0, rate=1.0, now=lambda: 0.0)
    for p in (_blk(0), _blk(100), _blk(200), _blk(0)):
        lens.on_access(p, n_resident=0)
    srv = obs.serve_metrics(0, kvlens=lens)
    try:
        base = f"http://127.0.0.1:{srv.port}/kvz"
        z = json.loads(urllib.request.urlopen(
            base, timeout=10).read().decode())
        assert [c["mult"] for c in z["curve"]] == [
            "0.5x", "1x", "2x", "4x", "8x"]
        assert z["samples"]["sampled"] == 4
        assert z["config"]["pool_blocks"] == 4
        prom = urllib.request.urlopen(
            base + "?format=prom", timeout=10).read().decode()
        assert 'dnn_tpu_kvlens_pred_hit_ratio{mult="2x"} 0.250000' in prom
        assert "dnn_tpu_kvlens_sampled_total 4" in prom
    finally:
        srv.close()


def test_cli_selftest_and_saved_dump(tmp_path):
    r = subprocess.run([sys.executable, "-m", "dnn_tpu.obs", "kvlens",
                        "--selftest"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "kvlens selftest ok" in r.stdout
    # the offline render path: a saved `curl .../kvz` dump
    lens = KVLens(4, BP, seed=0, rate=1.0, now=lambda: 0.0)
    for p in (_blk(0), _blk(100), _blk(0)):
        lens.on_access(p)
    path = tmp_path / "kvz.json"
    path.write_text(json.dumps(lens.summary()))
    r = subprocess.run([sys.executable, "-m", "dnn_tpu.obs", "kvlens",
                        str(path)], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0.5x" in r.stdout


# ----------------------------------------------------------------------
# in-process batcher e2e: the real store seams feed the lens
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def gpt_prepared():
    import jax

    from dnn_tpu.models import gpt

    cfg = gpt.GPTConfig(block_size=64, vocab_size=512, n_layer=2,
                        n_head=2, n_embd=64)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


def _mk_batcher(cfg, prepared, *, prefix_cache, paged_blocks=None):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    return ContinuousBatcher(cfg, prepared, slots=2,
                             max_len=cfg.block_size, prompt_pad=16,
                             kv="paged", block_len=16,
                             paged_blocks=paged_blocks,
                             prefix_cache=prefix_cache)


def test_batcher_forced_eviction_feeds_the_lens(gpt_prepared):
    cfg, prepared = gpt_prepared
    # store cap 4 blocks, 12 single-block tenants = a 3x working set —
    # continuous capacity eviction; explicit paged_blocks so the STORE
    # cap binds (auto-sizing would bound residency below prefix_cache)
    cache = 4
    pool = cache + 2 * (cfg.block_size // 16) + 1
    srv = _mk_batcher(cfg, prepared, prefix_cache=cache,
                      paged_blocks=pool)
    lens = srv._kvlens
    assert lens is not None, "lens must attach when obs is on at build"
    assert lens.pool_blocks == cache  # store cap < allocator here
    for rnd in range(2):
        for tenant in range(12):
            prompt = (np.arange(16) + 37 * tenant) % 510 + 1
            rid = srv.submit(prompt, 1)
            srv.drain()
            srv.claim(rid)
    # every turn's admission was one full-chunk access
    assert lens.accesses == 24 and lens.measured_accesses == 24
    assert lens.births > 0
    assert lens.evictions_by_cause.get("capacity", 0) > 0
    # round 2 re-touches evicted tenants within seconds: thrash bills
    assert lens.refetch_blocks > 0
    assert lens.thrash_chunk_seconds > 0  # prefill EMA was live
    mr = lens.measured_hit_ratio()
    assert mr is not None and 0.0 <= mr < 1.0
    # the curve gauges ride the serving registry next to kvtier's
    assert "dnn_tpu_kvlens_measured_hit_ratio" in srv._obs_gauges
    # the ledger saw the real lifecycle, causes attributed
    kinds = {e["kind"] for e in lens.ledger.events()}
    assert "birth" in kinds and "evict" in kinds and "refetch" in kinds


def test_curve_axis_is_the_effective_pool(gpt_prepared):
    cfg, prepared = gpt_prepared
    # auto-sized allocator: slots*(max_len/block_len)+1 = 9 blocks; a
    # nominal prefix_cache=64 cannot exceed what the allocator can
    # hold — the 1x label must pin to the allocator bound, not the knob
    srv = _mk_batcher(cfg, prepared, prefix_cache=64)
    lens = srv._kvlens
    assert lens is not None
    assert lens.pool_blocks == srv._allocator.n_blocks - 1 == 8
