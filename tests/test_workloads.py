"""ISSUE 14: the SLO observatory — workload suite, verdict engine,
incident forensics, perf ledger.

Covers: seeded arrival-process determinism (golden schedules — same
seed must yield bit-identical times on any host), scenario-script
determinism and the chat scenario's shared-prefix property, SLO-verdict
arithmetic goldens, incident-bundle write + CLI render + rejection of
non-bundles, ledger parsing against the REAL checked-in BENCH_r*.json
files and the committed RESULTS.md, centralized ratchet arithmetic,
one green end-to-end scenario (chat, with the new prefix hit/miss
counters live), the chaos-injected breach whose bundle is asserted by
READING IT BACK off disk, and the prefix-cache counter/gauge satellite
in serving.py.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dnn_tpu.workloads.arrivals import (
    bursty_arrivals,
    diurnal_envelope,
    poisson_arrivals,
    uniform,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------------------
# arrival processes: determinism is the contract
# ----------------------------------------------------------------------

def test_poisson_golden_schedule():
    """Same seed -> bit-identical arrival times, pinned against golden
    values (blake2s is stable across hosts and Python builds — a drift
    here means the determinism contract broke, not 'noise')."""
    a = poisson_arrivals(5.0, 4.0, seed=0)
    assert a == poisson_arrivals(5.0, 4.0, seed=0)
    assert len(a) == 21
    assert a[:4] == pytest.approx(
        [0.025864017292173185, 0.24144824739888726,
         0.3994130287519928, 0.48353397469164183], rel=1e-12)
    assert a == sorted(a) and all(0 <= t < 4.0 for t in a)
    assert poisson_arrivals(5.0, 4.0, seed=1) != a
    # distinct stream names never collide on one seed
    assert poisson_arrivals(5.0, 4.0, seed=0, name="other") != a


def test_poisson_validation():
    with pytest.raises(ValueError, match="rate_hz"):
        poisson_arrivals(0.0, 1.0, seed=0)
    with pytest.raises(ValueError, match="duration_s"):
        poisson_arrivals(1.0, -1.0, seed=0)


def test_bursty_golden_and_envelope_shape():
    b = bursty_arrivals(2.0, 10.0, seed=3, burst_factor=4.0,
                        period_s=10.0)
    assert b == bursty_arrivals(2.0, 10.0, seed=3, burst_factor=4.0,
                                period_s=10.0)
    assert len(b) == 32
    assert b[:3] == pytest.approx(
        [0.369734448695517, 0.4842764264016752, 0.809700084941519],
        rel=1e-12)
    assert b == sorted(b) and all(0 <= t < 10.0 for t in b)
    # the raised-cosine envelope peaks mid-period: the peak quarter
    # must be denser than the trough quarter (deterministic, so this
    # is a schedule property, not a statistical hope)
    trough = sum(1 for t in b if t < 2.5)
    peak = sum(1 for t in b if 3.75 <= t < 6.25)
    assert peak > trough, (peak, trough)


def test_diurnal_envelope_bounds():
    assert diurnal_envelope(0.0, 20.0, burst_factor=4.0) == \
        pytest.approx(1.0)
    assert diurnal_envelope(10.0, 20.0, burst_factor=4.0) == \
        pytest.approx(4.0)
    with pytest.raises(ValueError, match="period_s"):
        diurnal_envelope(1.0, 0.0)
    with pytest.raises(ValueError, match="burst_factor"):
        diurnal_envelope(1.0, 10.0, burst_factor=0.5)


def test_uniform_is_pure():
    assert uniform(7, "x", 0) == pytest.approx(0.8111295317148418,
                                               rel=1e-15)
    assert uniform(7, "x", 0) == uniform(7, "x", 0)
    assert uniform(7, "x", 1) != uniform(7, "x", 0)
    assert 0.0 <= uniform(7, "x", 1) < 1.0


# ----------------------------------------------------------------------
# scenario scripts: pure functions of the seed
# ----------------------------------------------------------------------

def _script_fingerprint(reqs):
    """Comparable view of a script (constraint objects are fresh
    instances per call — compare their presence, not identity)."""
    return [(round(r.at, 9), r.prompt.tobytes(), r.max_new, r.client,
             r.seed, sorted((r.opts or {}).keys()))
            for r in reqs]


def test_scenario_scripts_deterministic():
    from dnn_tpu.workloads.scenarios import SCENARIOS, get_scenario

    for name in sorted(SCENARIOS):
        sc = get_scenario(name, light=True)
        a = _script_fingerprint(sc.script(0))
        assert a == _script_fingerprint(sc.script(0)), name
        assert a != _script_fingerprint(sc.script(1)), name
        assert a, f"{name} produced an empty script"


def test_chat_script_shares_system_prefix():
    """The chat scenario's whole point: same-tenant turns share a
    chunk-aligned system prefix (the prefix cache's hit traffic),
    different tenants don't."""
    from dnn_tpu.workloads.scenarios import (
        PROMPT_PAD,
        _SYSTEM_CHUNKS,
        get_scenario,
    )

    sc = get_scenario("chat", light=True)
    reqs = sc.script(0)
    sys_len = _SYSTEM_CHUNKS * PROMPT_PAD
    by_tenant = {}
    for r in reqs:
        tenant = int(r.client[1:]) % 2
        by_tenant.setdefault(tenant, []).append(
            r.prompt[:sys_len].tobytes())
    for tenant, prefixes in by_tenant.items():
        assert len(set(prefixes)) == 1, f"tenant {tenant} prefix drifted"
    assert len(by_tenant) == 2
    t0, t1 = (v[0] for v in by_tenant.values())
    assert t0 != t1, "tenants must have distinct system prompts"
    for r in reqs:
        assert len(r.prompt) > sys_len  # every turn adds its own tail


def test_unknown_scenario_fails_loud():
    from dnn_tpu.workloads.scenarios import get_scenario

    with pytest.raises(ValueError, match="unknown scenario"):
        get_scenario("nope")


# ----------------------------------------------------------------------
# SLO verdict arithmetic
# ----------------------------------------------------------------------

def _recs():
    return [
        {"i": 0, "t": 0.0, "outcome": "ok", "tokens": 4,
         "ttft_s": 0.1, "itl_s": [0.05, 0.05, 0.05], "t_done": 0.3},
        {"i": 1, "t": 0.5, "outcome": "ok", "tokens": 4,
         "ttft_s": 0.9, "itl_s": [0.2], "t_done": 1.4},
        {"i": 2, "t": 1.0, "outcome": "rejected", "tokens": 0,
         "ttft_s": None, "itl_s": [], "t_done": 1.1},
    ]


def test_slo_verdict_golden():
    from dnn_tpu.obs.slo import SLOSpec, evaluate

    rep = evaluate("g", _recs(),
                   SLOSpec(ttft_s=1.0, itl_s=0.5, availability=0.9),
                   wall_s=2.0)
    by = {o["name"]: o for o in rep.objectives}
    # nearest-rank p95 of [0.1, 0.9] is 0.9; of the 4 itl samples, 0.2
    assert by["ttft_p95"]["measured"] == pytest.approx(0.9)
    assert by["ttft_p95"]["ok"]
    assert by["itl_p95"]["measured"] == pytest.approx(0.2)
    assert by["itl_p95"]["ok"]
    assert by["availability"]["measured"] == pytest.approx(2 / 3)
    assert not by["availability"]["ok"]
    assert by["lost"]["ok"]
    assert rep.goodput_tps == pytest.approx(8 / 2.0)
    assert not rep.ok
    # the breach window anchors on the bad records' completion times,
    # mapped onto the epoch axis when t0 is given
    rep2 = evaluate("g", _recs(), SLOSpec(availability=0.9),
                    wall_s=2.0, t0_epoch=1000.0)
    assert rep2.breach_window == pytest.approx((1001.1, 1001.1))


def test_slo_declared_ttft_with_no_completions_fails():
    from dnn_tpu.obs.slo import SLOSpec, evaluate

    recs = [{"i": 0, "t": 0.0, "outcome": "rejected", "tokens": 0,
             "ttft_s": None, "itl_s": [], "t_done": 0.1}]
    rep = evaluate("g", recs, SLOSpec(ttft_s=1.0), wall_s=1.0)
    by = {o["name"]: o for o in rep.objectives}
    assert not by["ttft_p95"]["ok"]   # declared objective, zero data
    assert not rep.ok


def test_slo_lost_asserts_zero_even_without_availability():
    from dnn_tpu.obs.slo import SLOSpec, evaluate

    recs = [{"i": 0, "t": 0.0, "outcome": None, "tokens": 0,
             "ttft_s": None, "itl_s": [], "t_done": None}]
    rep = evaluate("g", recs, SLOSpec(), wall_s=1.0)
    assert not rep.ok
    assert {o["name"]: o["ok"] for o in rep.objectives}["lost"] is False


def test_slo_goodput_floor():
    from dnn_tpu.obs.slo import SLOSpec, evaluate

    rep = evaluate("g", _recs(), SLOSpec(goodput_floor_tps=10.0),
                   wall_s=2.0)
    by = {o["name"]: o for o in rep.objectives}
    assert by["goodput_tps"]["measured"] == pytest.approx(4.0)
    assert not by["goodput_tps"]["ok"] and not rep.ok
    assert evaluate("g", _recs(), SLOSpec(goodput_floor_tps=3.0),
                    wall_s=2.0).ok
    with pytest.raises(ValueError, match="wall_s"):
        evaluate("g", _recs(), SLOSpec(), wall_s=0.0)


# ----------------------------------------------------------------------
# incident bundles: write, read BACK, render, reject garbage
# ----------------------------------------------------------------------

def test_incident_bundle_roundtrip_and_cli(tmp_path, capsys):
    from dnn_tpu.obs.flight import FlightRecorder
    from dnn_tpu.obs.slo import (
        SLOSpec,
        evaluate,
        load_incident,
        render_incident,
        write_incident_bundle,
    )

    fr = FlightRecorder(capacity=64)
    import time as _t

    now = _t.time()
    fr.record("chaos_inject", fault="step_fault", n=2)
    fr.record("worker_died", requeue=True)
    rep = evaluate("synthetic", _recs(), SLOSpec(availability=0.99),
                   wall_s=2.0, t0_epoch=now - 1.1)  # bad t_done -> now
    assert not rep.ok and rep.breach_window is not None
    d = str(tmp_path / "bundle")
    write_incident_bundle(d, rep, flight=fr, records=_recs())
    # read the ARTIFACT back — the assertion the acceptance demands
    b = load_incident(d)
    assert b["manifest"]["report"]["ok"] is False
    kinds = [e["kind"] for e in b["flight"]]
    assert "chaos_inject" in kinds and "worker_died" in kinds
    text = render_incident(b)
    assert "SLO BREACH" in text and "chaos_inject" in text
    assert "availability" in text
    # the CLI renders the same bundle
    from dnn_tpu.obs.__main__ import main as obs_main

    rc = obs_main(["incident", d])
    assert rc == 0
    out = capsys.readouterr().out
    assert "SLO BREACH" in out and "worker_died" in out
    rc = obs_main(["incident", d, "--json"])
    assert rc == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["manifest"]["report"]["scenario"] == "synthetic"


def test_incident_bundle_rejects_non_bundle(tmp_path):
    from dnn_tpu.obs.slo import load_incident

    with pytest.raises(ValueError, match="not an incident bundle"):
        load_incident(str(tmp_path))
    (tmp_path / "manifest.json").write_text('{"kind": "other"}')
    with pytest.raises(ValueError, match="not an incident manifest"):
        load_incident(str(tmp_path))


def test_incident_bundle_ok_report_snapshot(tmp_path):
    """A non-breach report still snapshots (the runner only writes on
    breach, but the writer itself must not assume one — the whole ring
    lands when there is no window to filter to)."""
    from dnn_tpu.obs.flight import FlightRecorder
    from dnn_tpu.obs.slo import (
        SLOSpec,
        evaluate,
        load_incident,
        write_incident_bundle,
    )

    fr = FlightRecorder(capacity=8)
    fr.record("admit", rid=1)
    rep = evaluate("ok-case", _recs()[:2], SLOSpec(availability=0.5),
                   wall_s=2.0)
    assert rep.ok
    d = str(tmp_path / "b2")
    write_incident_bundle(d, rep, flight=fr)
    b = load_incident(d)
    assert b["manifest"]["report"]["ok"] is True
    assert [e["kind"] for e in b["flight"]] == ["admit"]


# ----------------------------------------------------------------------
# ledger: the real checked-in artifacts parse
# ----------------------------------------------------------------------

def test_ledger_parses_real_bench_rounds():
    from benchmarks.ledger import bench_rounds

    rounds = bench_rounds(REPO)
    nums = [e["round"] for e in rounds]
    assert nums == sorted(nums) and len(nums) >= 5
    r1 = next(e for e in rounds if e["round"] == 1)
    assert isinstance(r1["value"], (int, float))
    assert r1["vs_baseline"] > 1.0  # the committed on-chip round
    # r02 crashed before printing a row: present, honestly marked
    r2 = next(e for e in rounds if e["round"] == 2)
    assert r2["metric"] is None and "no row" in r2["substrate"]
    r5 = next(e for e in rounds if e["round"] == 5)
    assert r5["substrate"] == "cpu" and r5["stale_tpu_reference"]


def test_ledger_run_rows_parse_results_md():
    from benchmarks.ledger import run_rows

    rows = run_rows(state_path=os.path.join(REPO, "does-not-exist"),
                    results_path=os.path.join(REPO, "benchmarks",
                                              "RESULTS.md"))
    by = {r["config"]: r for r in rows}
    assert "gpt2_fwd" in by
    assert isinstance(by["gpt2_fwd"]["value"], float)
    # the detail-cell k=v extraction the ratchets read
    assert by["obs_overhead"]["ok"] is True


def test_ledger_ratchet_arithmetic():
    from benchmarks.ledger import Ratchet, check_ratchets

    rows = [{"config": "decode_mbu", "value": 27.0},
            {"config": "step_timeline", "value": 12.0},
            {"config": "workload_chat", "ok": True}]
    by = {v["ratchet"]: v for v in check_ratchets(rows)}
    assert by["decode_mbu_floor"]["status"] == "ok"
    assert by["decode_mbu_floor"]["threshold"] == pytest.approx(10.0)
    assert by["host_fraction_ceiling"]["status"] == "ok"
    assert by["workload_chat"]["status"] == "ok"
    assert by["chaos_availability_floor"]["status"] == "missing"
    # a regression FAILS — the centralized assert is real
    assert Ratchet(
        "x", "decode_mbu", "value", ">=", lambda: 10.0).evaluate(
        [{"config": "decode_mbu", "value": 5.0}])["status"] == "FAIL"
    assert Ratchet(
        "x", "step_timeline", "value", "<=", lambda: 40.0).evaluate(
        [{"config": "step_timeline", "value": 55.0}])["status"] == "FAIL"


def test_ledger_cli_runs_green_on_checked_in_artifacts():
    """The CLI over the REAL repo state: parses, renders, exits 0
    (missing rows are reported, not failed, without --strict)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "ledger.py"),
         "--assert"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "Perf trajectory" in proc.stdout
    assert "| r01 " in proc.stdout


def test_run_all_scenarios_filter_rejects_unknown():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "run_all.py"),
         "--scenarios", "not_a_scenario"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode != 0
    assert "unknown scenario" in (proc.stderr + proc.stdout)


# ----------------------------------------------------------------------
# prefix-cache counters + gauge (the serving.py satellite)
# ----------------------------------------------------------------------

def test_prefix_counters_and_hit_ratio_gauge():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                        n_head=1, n_embd=16)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=24,
                            prompt_pad=4, prefix_cache=2)
    p = np.arange(1, 9, dtype=np.int32)  # 2 full chunks
    srv.submit(p, max_new_tokens=2)
    srv.drain()
    assert (srv.prefix_hits, srv.prefix_misses) == (0, 1)
    assert srv._prefix_ratio_read() == 0.0
    srv.submit(p, max_new_tokens=2)  # identical prompt: full-chunk hit
    srv.drain()
    assert (srv.prefix_hits, srv.prefix_misses) == (1, 1)
    assert srv._prefix_ratio_read() == pytest.approx(0.5)
    # the gauge is registered (weakly) under the public name
    assert "dnn_tpu_prefix_hit_ratio" in srv._obs_gauges
    assert srv._obs_gauges["dnn_tpu_prefix_hit_ratio"]() == \
        pytest.approx(0.5)
    # capacity 2: a different 2-chunk prompt's inserts evict
    before = srv.prefix_evictions
    srv.submit(np.arange(20, 28, dtype=np.int32), max_new_tokens=2)
    srv.drain()
    assert srv.prefix_evictions > before
    # the registry counters moved with the attrs
    from dnn_tpu import obs

    m = obs.metrics()
    if m is not None:
        snap = m.snapshot()["counters"]
        assert snap.get("serving.prefix_misses_total", 0) >= 1
        assert snap.get("serving.prefix_evictions_total", 0) >= 1


def test_prefix_ratio_gauge_absent_without_cache():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=32, vocab_size=64, n_layer=1,
                        n_head=1, n_embd=16)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=16,
                            prompt_pad=4)
    assert "dnn_tpu_prefix_hit_ratio" not in srv._obs_gauges


# ----------------------------------------------------------------------
# end to end: one green scenario, one asserted breach
# ----------------------------------------------------------------------

@pytest.mark.timeout(300)
def test_chat_scenario_green_end_to_end():
    """The light chat scenario through the real runner + in-process
    LMServer: verdict ok, nothing lost, prefix cache measurably hit,
    live burn-rate gauges ride the report."""
    from dnn_tpu import obs
    from dnn_tpu.workloads import get_scenario, run_scenario

    obs.set_enabled(True)  # flight/burn-rate surfaces are part of what
    # this test asserts — an earlier module's gate flip must not leak in
    res = run_scenario(get_scenario("chat", light=True), seed=0)
    rep = res["report"]
    assert rep.ok, rep.to_dict()
    assert rep.lost == 0 and rep.completed == rep.requests
    assert rep.goodput_tps > 0
    assert res["bundle"] is None  # no breach, no bundle
    assert res["extras"]["prefix_hit_ratio"] > 0.5, res["extras"]
    assert rep.burn_rates is not None \
        and "availability" in rep.burn_rates
    # every record resolved with timing data
    for r in res["records"]:
        assert r["outcome"] == "ok"
        assert r["ttft_s"] is not None and r["ttft_s"] >= 0


@pytest.mark.timeout(300)
def test_scenario_against_real_grpc_daemon():
    """The router-fleet path: the same chat script fired at a LIVE
    gRPC daemon (`target="host:port"`) instead of the scenario's own
    in-process server — per-request GenerateStream clients, wire-true
    TTFT/ITL, same verdict machinery. This is how a scenario points at
    a PR-12 router front door."""
    import jax

    from dnn_tpu import obs
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background
    from dnn_tpu.workloads import get_scenario, run_scenario
    from dnn_tpu.workloads.scenarios import PROMPT_PAD, _cfg

    obs.set_enabled(True)
    cfg = _cfg()
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    port = 59941  # distinct from the chaos/relay/fleet probe ranges
    _t, stop = start_lm_server_in_background(
        cfg, prepared, port=port, slots=4, max_len=64,
        prompt_pad=PROMPT_PAD, prefix_cache=8, temperature=0.0)
    try:
        sc = get_scenario("chat", light=True)
        res = run_scenario(sc, seed=0, target=f"127.0.0.1:{port}")
        rep = res["report"]
        assert rep.lost == 0
        assert rep.completed == rep.requests, rep.to_dict()
        assert rep.ok, rep.to_dict()
        for r in res["records"]:
            assert r["ttft_s"] is not None  # streaming gave real TTFT
    finally:
        stop()


@pytest.mark.timeout(300)
def test_breach_scenario_bundle_asserted_from_artifact():
    """The chaos-injected breach end to end via the PROBE (the same
    path the run_all row takes): the verdict is a breach, and `ok`
    comes from reading the bundle back off disk — manifest verdict,
    chaos_inject events in the dumped timeline, CLI render."""
    from benchmarks.workload_probe import measure

    from dnn_tpu import obs

    obs.set_enabled(True)  # the bundle reads the flight ring back
    row = measure("breach_chaos", light=True)
    assert row["expect_breach"] is True
    assert row["slo_verdict"] == "breach"
    assert row["ok"] is True, row
    assert row["reconstructed"] is True
    assert row["chaos_events_in_bundle"] >= 1
    assert row["lost"] == 0  # failures are EXPLICIT even mid-storm
    # and the CLI renders the artifact the probe verified
    proc = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "incident",
         row["bundle"]],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "SLO BREACH" in proc.stdout
    assert "chaos_inject" in proc.stdout
