"""Fixture suite for the concurrency-hazard analyzer (ISSUE 10).

One known-bad / known-good pair per CON rule, the THREE historical
shipped bugs (PR 7's ShmRing event-loop deadlock, PR 4's unguarded
set_result worker-killer, PR 7 r2's cancelled-handler ticket-slot
leak) reintroduced as fixtures and each flagged by its rule, the
protocol state-machine goldens (including the "unsettled half-open
probe slot sheds traffic forever" bug as a PRO002 model-check
failure), the loop-lag sanitizer's unit + endpoint-readback behavior,
self-lint over the serving stack modulo baseline, and the CLI's
--diff / --format sarif contracts.
"""

import asyncio
import json
import os
import textwrap
import time

import pytest

from dnn_tpu.analysis.lint import lint_paths, lint_source

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "dnn_tpu")
BASELINE = os.path.join(PKG_DIR, "analysis", "baseline.json")


def rules_of(src):
    return sorted({f.rule for f in lint_source(textwrap.dedent(src), "t")})


# ----------------------------------------------------------------------
# rule fixtures: (rule, known-bad, known-good twin)
# ----------------------------------------------------------------------

FIXTURES = {
    "CON001": (
        """
        import time
        async def handler(x):
            time.sleep(0.5)
            return x
        """,
        """
        import asyncio
        import time
        async def handler(x):
            await asyncio.to_thread(time.sleep, 0.5)
            return x
        """,
    ),
    "CON002": (
        """
        def publish(fut, tokens):
            fut.set_result(tokens)
        """,
        """
        def publish(fut, tokens):
            if not fut.done():
                fut.set_result(tokens)
        """,
    ),
    "CON003": (
        """
        async def forward(sender, call, y, rid):
            request = sender.make_request_nowait(y, rid)
            resp = await call(request)
            sender.sent_ok(request)
            return resp
        """,
        """
        async def forward(sender, call, y, rid):
            request = sender.make_request_nowait(y, rid)
            ok = False
            try:
                resp = await call(request)
                ok = True
                return resp
            finally:
                if ok:
                    sender.sent_ok(request)
                else:
                    sender.cleanup(request)
        """,
    ),
    "CON004": (
        """
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:
                    pass
        def g():
            with B:
                with A:
                    pass
        """,
        """
        import threading
        A = threading.Lock()
        B = threading.Lock()
        def f():
            with A:
                with B:
                    pass
        def g():
            with A:
                with B:
                    pass
        """,
    ),
    "CON005": (
        """
        import threading
        class Worker:
            def __init__(self):
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
            def _run(self):
                self.state = "running"
            async def handle(self):
                self.state = "served"
        """,
        """
        import threading
        class Worker:
            def __init__(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
            def _run(self):
                with self._lock:
                    self.state = "running"
            async def handle(self):
                with self._lock:
                    self.state = "served"
        """,
    ),
    "CON006": (
        """
        import threading
        class Ring:
            def __init__(self):
                self._cond = threading.Condition()
            def release(self):
                self._cond.notify_all()
        """,
        """
        import threading
        class Ring:
            def __init__(self):
                self._cond = threading.Condition()
            def release(self):
                with self._cond:
                    self._cond.notify_all()
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fixture_pair(rule):
    bad, good = FIXTURES[rule]
    assert rule in rules_of(bad), f"{rule} must flag its bad fixture"
    assert rules_of(good) == [], \
        f"{rule} good twin must be clean, got {rules_of(good)}"


# extra per-rule behaviors beyond the canonical pair -------------------

def test_con001_awaited_and_referenced_forms_clean():
    # awaiting an asyncio primitive and PASSING a blocking function by
    # reference to to_thread are the two sanctioned forms
    src = """
    import asyncio
    import queue
    q = queue.Queue()
    async def f(evt):
        await asyncio.wait_for(evt.wait(), 1.0)
        item = await asyncio.to_thread(q.get)
        return item
    """
    assert rules_of(src) == []


def test_con001_typed_receiver_and_nonblocking_forms():
    bad = """
    import queue
    q = queue.Queue()
    async def f():
        return q.get()
    """
    assert "CON001" in rules_of(bad)
    good = """
    import queue
    q = queue.Queue()
    async def f():
        return q.get(block=False)
    """
    assert rules_of(good) == []


def test_con002_try_except_guard_accepted():
    src = """
    def publish(fut, tokens):
        try:
            fut.set_result(tokens)
        except Exception:
            pass
    """
    assert rules_of(src) == []


def test_con002_settle_inside_except_handler_not_guarded():
    # a handler does not catch exceptions raised in its OWN body — a
    # cleanup-path settle inside `except:` is exactly where the PR 4
    # bug class hides (review-round find on this rule's first cut)
    src = """
    def run(fut, step):
        try:
            fut.set_result(step())
        except Exception as e:
            fut.set_exception(e)
    """
    assert "CON002" in rules_of(src)
    good = """
    def run(fut, step):
        try:
            fut.set_result(step())
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
    """
    assert rules_of(good) == []


def test_con001_to_thread_closure_fix_accepted():
    # the sanctioned fix written as a LOCAL closure must not flag
    # (review-round find: only the async def's own body is loop
    # context)...
    src = """
    import asyncio
    import queue
    q = queue.Queue()
    async def handler():
        def work():
            return q.get()
        return await asyncio.to_thread(work)
    """
    assert rules_of(src) == []
    # ...but CALLING the blocking closure directly on the loop still
    # flags, through the blocking-closure propagation
    bad = """
    import queue
    q = queue.Queue()
    async def handler():
        def work():
            return q.get()
        return work()
    """
    assert "CON001" in rules_of(bad)


def test_con005_single_writer_annotation():
    bad, _good = FIXTURES["CON005"]
    annotated = bad.replace('self.state = "running"',
                            'self.state = "running"  # conc: single-writer')
    annotated = annotated.replace(
        'self.state = "served"',
        'self.state = "served"  # conc: single-writer')
    assert rules_of(annotated) == []


def test_con006_nondaemon_thread_without_join():
    bad = """
    import threading
    def work():
        pass
    def start():
        t = threading.Thread(target=work)
        t.start()
    """
    assert "CON006" in rules_of(bad)
    good = """
    import threading
    def work():
        pass
    def start():
        t = threading.Thread(target=work)
        t.start()
        t.join()
    """
    assert rules_of(good) == []


# ----------------------------------------------------------------------
# the three historical shipped bugs, reintroduced as fixtures
# ----------------------------------------------------------------------

# PR 7 e2e-verify find: ShmRing.write (a blocking Condition wait) ran on
# the server event loop that processes the very acks that free slots —
# a deadlock until the 30 s ring timeout. Through one level of
# indirection, exactly what the per-module call chain resolves.
HIST_SHMRING_DEADLOCK = """
class Forwarder:
    def __init__(self, slots):
        self._ring = ShmRing(slots)
    def _send(self, view):
        return self._ring.write(view)
    async def forward(self, view):
        seg = self._send(view)
        return seg
"""

# PR 4 latent worker-killer: set_result on a future its caller had
# deadline-cancelled raised InvalidStateError and killed the batcher
# thread (every later request then hung to its timeout).
HIST_SET_RESULT_RACE = """
def publish_done(futures, batcher):
    for rid in list(futures):
        tokens, _reason, _lps = batcher.claim(rid)
        fut = futures.pop(rid)
        fut.set_result(tokens)
"""

# PR 7 review-round-2 find: the cancelled _forward handler (upstream
# deadline mid-forward) skipped both release paths — success AND
# except(Exception) — leaking the ticket slot; 4 cancellations wedged
# the 4-slot ring for good. Only a finally is cancel-safe.
HIST_SLOT_LEAK = """
async def _forward(sender, call, y, rid):
    request = sender.make_request_nowait(y, rid)
    try:
        resp = await call(request)
        sender.sent_ok(request)
        return resp
    except Exception:
        sender.cleanup(request)
        raise
"""

HISTORICAL = {
    "CON001": HIST_SHMRING_DEADLOCK,
    "CON002": HIST_SET_RESULT_RACE,
    "CON003": HIST_SLOT_LEAK,
}


@pytest.mark.parametrize("rule", sorted(HISTORICAL))
def test_historical_bug_flagged_by_its_rule(rule):
    assert rule in rules_of(HISTORICAL[rule]), \
        f"the reintroduced historical bug must be a {rule} finding"


@pytest.mark.parametrize("rule", sorted(HISTORICAL))
def test_historical_bug_fails_the_gate(rule, tmp_path):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / f"hist_{rule.lower()}.py"
    bad.write_text(textwrap.dedent(HISTORICAL[rule]))
    assert main([str(bad), "--no-program", "--no-protocol",
                 "--no-baseline"]) == 1


# ----------------------------------------------------------------------
# protocol state machines
# ----------------------------------------------------------------------

def test_protocol_tables_model_check_clean():
    from dnn_tpu.analysis.protocol import MACHINES, check_machine

    for m in MACHINES:
        assert check_machine(m) == [], f"machine {m.name} must be sound"


def test_protocol_audit_clean_on_head():
    """Every declared machine's code sites map to declared edges and
    every edge has a site — the table/code correspondence on HEAD."""
    from dnn_tpu.analysis.protocol import run_protocol_audit

    report, findings = run_protocol_audit(REPO_ROOT)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in findings)
    assert all(m["clean"] for m in report["machines"])
    assert {m["name"] for m in report["machines"]} == {
        "circuit_breaker", "supervisor", "drain", "relay_accept_window",
        "replica_lifecycle", "router", "kvtier_lease"}


def test_pro002_unsettled_probe_slot_is_a_model_failure():
    """The PR 8 review-round bug as a model-check failure: remove
    half_open's exits (what the consumed-then-delegated probe slot
    effectively did) and the breaker machine has an absorbing
    non-terminal state — it sheds 100% of traffic forever."""
    import dataclasses

    from dnn_tpu.analysis.protocol import BREAKER, check_machine

    buggy = dataclasses.replace(
        BREAKER,
        edges=tuple(e for e in BREAKER.edges if e.src != "half_open"))
    findings = check_machine(buggy)
    assert any(f.rule == "PRO002" and "half_open" in f.message
               for f in findings)


def test_pro001_unreachable_state():
    from dnn_tpu.analysis.protocol import Edge, Machine, check_machine

    m = Machine(name="t", states=("a", "b", "orphan"), initial="a",
                terminal=("b",), edges=(Edge("a", "go", "b"),))
    findings = check_machine(m)
    assert any(f.rule == "PRO001" and "orphan" in f.message
               for f in findings)


def test_pro003_undeclared_transition_site():
    from dnn_tpu.analysis.protocol import (
        Edge,
        Machine,
        check_machine_sites,
    )

    m = Machine(name="t", states=("a", "b"), initial="a",
                terminal=("b",), edges=(Edge("a", "go", "b"),),
                module="x.py", cls="T", state_attr="_state")
    src = textwrap.dedent("""
        class T:
            def __init__(self):
                self._state = "a"
            def go(self):
                self._state = "b"
            def wedge(self):
                self._state = "zombie"
    """)
    findings = check_machine_sites(m, REPO_ROOT, src=src)
    assert any(f.rule == "PRO003" and "zombie" in f.message
               for f in findings)


def test_pro004_stale_edge():
    from dnn_tpu.analysis.protocol import (
        Edge,
        Machine,
        check_machine_sites,
    )

    m = Machine(name="t", states=("a", "b", "c"), initial="a",
                terminal=("c",),
                edges=(Edge("a", "go", "b"), Edge("b", "fin", "c")),
                module="x.py", cls="T", state_attr="_state")
    src = textwrap.dedent("""
        class T:
            def __init__(self):
                self._state = "a"
            def go(self):
                self._state = "b"
    """)
    findings = check_machine_sites(m, REPO_ROOT, src=src)
    assert any(f.rule == "PRO004" and "fin" in f.message
               for f in findings)


# ----------------------------------------------------------------------
# loop-lag sanitizer (analysis/sanitize.py)
# ----------------------------------------------------------------------

def test_sanitizer_catches_planted_blocking_callback():
    from dnn_tpu.analysis.sanitize import LoopLagSanitizer

    async def scenario():
        s = LoopLagSanitizer(threshold_s=0.05, interval_s=0.01,
                             where="test-sanitize").install()
        await asyncio.sleep(0.05)
        time.sleep(0.3)  # the planted blocking callback
        await asyncio.sleep(0.05)
        s.stop()
        return s

    s = asyncio.run(scenario())
    assert s.breaches >= 1
    assert s.max_lag_s >= 0.2
    with pytest.raises(AssertionError, match="blocked the loop"):
        s.assert_bounded(0.1)
    # the breach landed in the flight ring (the probes' artifact)
    from dnn_tpu import obs

    evs = obs.flight.recorder().events(kind="loop_lag")
    assert any(e.get("where") == "test-sanitize" for e in evs)
    ons = obs.flight.recorder().events(kind="loop_sanitize_on")
    assert any(e.get("where") == "test-sanitize" for e in ons)


def test_sanitizer_clean_loop_passes_bound():
    from dnn_tpu.analysis.sanitize import LoopLagSanitizer

    async def scenario():
        s = LoopLagSanitizer(threshold_s=0.2, interval_s=0.01,
                             where="test-clean").install()
        for _ in range(10):
            await asyncio.sleep(0.01)
        s.stop()
        return s

    s = asyncio.run(scenario())
    assert s.breaches == 0
    s.assert_bounded(1.0)  # generous: CI scheduler jitter is not a breach


def test_sanitizer_event_cap_bounds_ring_traffic():
    from dnn_tpu.analysis.sanitize import LoopLagSanitizer

    async def scenario():
        s = LoopLagSanitizer(threshold_s=0.01, interval_s=0.005,
                             max_events=3, where="test-cap").install()
        for _ in range(8):
            await asyncio.sleep(0.01)  # let the rearmed tick schedule
            time.sleep(0.03)           # ...then breach it
        await asyncio.sleep(0.01)
        s.stop()
        return s

    s = asyncio.run(scenario())
    assert s.breaches >= 4
    from dnn_tpu import obs

    evs = [e for e in obs.flight.recorder().events(kind="loop_lag")
           if e.get("where") == "test-cap"]
    assert len(evs) <= 3  # bounded: a wedged loop can't flood the ring


def test_sanitizer_endpoint_readback():
    """read_endpoint reads installed/breaches/max_lag off a served
    /debugz — the exact readback the chaos/transport probes assert."""
    from dnn_tpu import obs
    from dnn_tpu.analysis.sanitize import LoopLagSanitizer, read_endpoint

    srv = obs.serve_metrics(0)
    try:
        async def scenario():
            s = LoopLagSanitizer(threshold_s=0.05, interval_s=0.01,
                                 where="test-endpoint").install()
            await asyncio.sleep(0.02)
            time.sleep(0.2)
            await asyncio.sleep(0.02)
            s.stop()
            return s

        asyncio.run(scenario())
        rec = read_endpoint(f"http://127.0.0.1:{srv.port}")
        assert rec["installed"] is True
        assert rec["breaches"] >= 1
        assert rec["max_lag_ms"] >= 100.0
    finally:
        srv.close()


def test_sanitizer_env_gate(monkeypatch):
    from dnn_tpu.analysis import sanitize

    monkeypatch.delenv(sanitize.ENV_GATE, raising=False)
    assert sanitize.maybe_install() is None  # off by default
    monkeypatch.setenv(sanitize.ENV_GATE, "1")
    monkeypatch.setenv(sanitize.ENV_THRESHOLD, "0.5")

    async def scenario():
        s = sanitize.maybe_install(where="test-env")
        assert s is not None and s.threshold_s == 0.5
        s.stop()

    asyncio.run(scenario())


# ----------------------------------------------------------------------
# self-lint + baseline discipline over the serving stack
# ----------------------------------------------------------------------

def test_serving_stack_con_clean_modulo_baseline():
    """The burn-down contract (ISSUE 10 satellite): zero unjustified
    CON/protocol findings over comm/, runtime/lm_server, chaos/ — every
    surviving finding is baselined WITH a justification."""
    from dnn_tpu.analysis.findings import (
        diff_against_baseline,
        load_baseline,
    )
    from dnn_tpu.analysis.protocol import run_protocol_audit

    targets = [os.path.join(PKG_DIR, "comm"),
               os.path.join(PKG_DIR, "chaos"),
               os.path.join(PKG_DIR, "obs"),
               os.path.join(PKG_DIR, "runtime", "lm_server.py")]
    findings = lint_paths(targets, repo_root=REPO_ROOT)
    _report, proto = run_protocol_audit(REPO_ROOT)
    entries = load_baseline(BASELINE)
    new, suppressed, _stale = diff_against_baseline(
        list(findings) + list(proto), entries)
    assert not new, "unbaselined findings:\n" + "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in new)
    for e in entries:
        assert str(e.get("justification", "")).strip(), e


# ----------------------------------------------------------------------
# CLI: exit codes, --diff, --format sarif
# ----------------------------------------------------------------------

@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_cli_nonzero_per_rule(rule, tmp_path):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / f"inject_{rule.lower()}.py"
    bad.write_text(textwrap.dedent(FIXTURES[rule][0]))
    assert main([str(bad), "--no-program", "--no-protocol",
                 "--no-baseline"]) == 1
    good = tmp_path / f"clean_{rule.lower()}.py"
    good.write_text(textwrap.dedent(FIXTURES[rule][1]))
    assert main([str(good), "--no-program", "--no-protocol",
                 "--no-baseline"]) == 0


def test_cli_sarif_output(tmp_path, capsys):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / "user_async.py"
    bad.write_text(textwrap.dedent(FIXTURES["CON001"][0]))
    rc = main([str(bad), "--no-program", "--no-protocol",
               "--no-baseline", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "CON001"
    assert results[0]["level"] == "error"
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] >= 1
    rules = {r["id"] for r in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert "CON001" in rules

    good = tmp_path / "user_async_ok.py"
    good.write_text(textwrap.dedent(FIXTURES["CON001"][1]))
    rc = main([str(good), "--no-program", "--no-protocol",
               "--no-baseline", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["runs"][0]["results"] == []


def test_cli_sarif_carries_suppressions(capsys):
    """Baselined findings ride the SARIF report as suppressed notes —
    enumerated, not hidden, same policy as the text report."""
    from dnn_tpu.analysis.__main__ import main

    rc = main(["--no-program", "--format", "sarif"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    notes = [r for r in doc["runs"][0]["results"]
             if r["level"] == "note"]
    assert notes, "the baselined findings must appear as notes"
    assert all(r["suppressions"][0]["justification"] for r in notes)


def test_cli_diff_mode(tmp_path):
    """--diff REV lints only the package files changed since REV
    (program pass auto-skipped); the working tree's own diff against
    HEAD must pass the gate — tests/benchmarks (which plant hazard
    fixtures on purpose) are outside diff scope like they are outside
    the default gate's."""
    import subprocess

    from dnn_tpu.analysis.__main__ import changed_files, main

    git = subprocess.run(["git", "-C", REPO_ROOT, "rev-parse", "HEAD"],
                         capture_output=True, text=True)
    if git.returncode != 0:
        pytest.skip("no git repo / rev available")
    files = changed_files("HEAD", REPO_ROOT)
    assert all(f.endswith(".py") and os.path.exists(
        os.path.join(REPO_ROOT, f)) for f in files)
    assert main(["--diff", "HEAD"]) == 0
