"""Torch-layout export tests: the hand-written .pth writer must satisfy
BOTH readers — real torch.load (torch is installed in this env) and this
package's torch-free parser — and the layout converters must invert the
import path exactly."""

import numpy as np
import pytest

import jax

from dnn_tpu.io.checkpoint import (
    cifar_params_from_torch_state_dict,
    gpt_params_from_state_dict,
    load_pth_state_dict,
)
from dnn_tpu.io.torch_export import (
    cifar_state_dict_from_params,
    gpt_state_dict_from_params,
    save_pth,
)

torch = pytest.importorskip("torch")


def _tree_equal(a, b):
    ja, jb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(ja) == len(jb)
    for x, y in zip(ja, jb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_pth_roundtrips_through_torch_load(tmp_path):
    rng = np.random.default_rng(0)
    sd = {
        "a.weight": rng.normal(size=(4, 3)).astype(np.float32),
        "a.bias": rng.normal(size=(300,)).astype(np.float32),  # numel > 255
        "b.ids": np.arange(6, dtype=np.int64).reshape(2, 3),
        "c.flag": np.array([True, False]),
        "d.scalar": np.float32(2.5).reshape(()),
    }
    path = str(tmp_path / "export.pth")
    save_pth(path, sd)

    loaded = torch.load(path, map_location="cpu", weights_only=True)
    assert set(loaded) == set(sd)
    for k, v in sd.items():
        np.testing.assert_array_equal(loaded[k].numpy(), v)


def test_save_pth_roundtrips_through_own_reader(tmp_path):
    rng = np.random.default_rng(1)
    sd = {"x": rng.normal(size=(5, 7)).astype(np.float32),
          "y": rng.integers(0, 100, (3,)).astype(np.int32)}
    path = str(tmp_path / "own.pth")
    save_pth(path, sd)
    back = load_pth_state_dict(path)
    for k, v in sd.items():
        np.testing.assert_array_equal(back[k], v)


def test_cifar_export_import_is_identity():
    from dnn_tpu.models import cifar

    params = cifar.init(jax.random.PRNGKey(0))
    sd = cifar_state_dict_from_params(params)
    assert sd["conv1.weight"].shape == (32, 3, 3, 3)   # OIHW
    assert sd["fc1.weight"].shape == (512, 4096)
    back = cifar_params_from_torch_state_dict(sd)
    _tree_equal(params, back)


def test_cifar_export_matches_torch_forward(tmp_path):
    """The exported state dict, loaded into an equivalent torch model, must
    predict exactly like our NHWC model on the same image — the numerical
    basis of the reference-node interop."""
    import torch.nn as tnn
    import torch.nn.functional as tF

    from dnn_tpu.models import cifar

    class TorchCifar(tnn.Module):
        # same architecture as the reference NeuralNetwork
        # (cifar_model_parts.py:6-26), re-declared here for the test
        def __init__(self):
            super().__init__()
            self.conv1 = tnn.Conv2d(3, 32, 3, padding=1)
            self.conv2 = tnn.Conv2d(32, 64, 3, padding=1)
            self.fc1 = tnn.Linear(64 * 8 * 8, 512)
            self.fc2 = tnn.Linear(512, 10)

        def forward(self, x):
            x = tF.max_pool2d(tF.relu(self.conv1(x)), 2)
            x = tF.max_pool2d(tF.relu(self.conv2(x)), 2)
            x = x.reshape(-1, 64 * 8 * 8)
            x = tF.relu(self.fc1(x))
            return tF.softmax(self.fc2(x), dim=1)

    params = cifar.init(jax.random.PRNGKey(3))
    path = str(tmp_path / "cifar_export.pth")
    save_pth(path, cifar_state_dict_from_params(params))

    tm = TorchCifar()
    tm.load_state_dict(torch.load(path, map_location="cpu", weights_only=True))
    tm.eval()

    x_nhwc = np.asarray(cifar.example_input(batch_size=4, rng=jax.random.PRNGKey(9)))
    ours = np.asarray(cifar.apply(params, x_nhwc))
    with torch.no_grad():
        theirs = tm(torch.from_numpy(x_nhwc.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-5, rtol=1e-4)
    np.testing.assert_array_equal(ours.argmax(1), theirs.argmax(1))


def test_gpt_export_import_is_identity():
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    for layout in ("conv1d", "linear"):
        sd = gpt_state_dict_from_params(params, layout=layout)
        back = gpt_params_from_state_dict(sd, n_layer=cfg.n_layer)
        _tree_equal(params, back)


def test_gpt_export_loads_into_transformers(tmp_path):
    """HF-layout export must load into a real GPT2LMHeadModel and agree on
    logits."""
    from transformers import GPT2Config, GPT2LMHeadModel

    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(1), cfg)
    sd = {f"transformer.{k}" if not k.startswith("lm_head") else k: v
          for k, v in gpt_state_dict_from_params(params, layout="conv1d").items()}
    path = str(tmp_path / "gpt_export.pth")
    save_pth(path, sd)

    hf_cfg = GPT2Config(
        vocab_size=cfg.vocab_size, n_positions=cfg.block_size,
        n_embd=cfg.n_embd, n_layer=cfg.n_layer, n_head=cfg.n_head,
        attn_pdrop=0.0, embd_pdrop=0.0, resid_pdrop=0.0,
    )
    hf = GPT2LMHeadModel(hf_cfg)
    missing, unexpected = hf.load_state_dict(
        torch.load(path, map_location="cpu", weights_only=True), strict=False
    )
    # HF registers attn.bias/masked_bias buffers we don't export; nothing
    # else may be missing, and nothing may be unexpected.
    assert not unexpected
    assert all(".attn." in m or m.endswith(".bias") for m in missing), missing
    hf.eval()

    ids = np.asarray([[1, 2, 3, 4, 5]], np.int64)
    ours = np.asarray(gpt.make_apply(cfg)(params, ids.astype(np.int32)))
    with torch.no_grad():
        theirs = hf(torch.from_numpy(ids)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=2e-3, rtol=1e-3)


def test_llama_family_export_import_is_identity():
    """llama_state_dict_from_params must invert
    llama_params_from_state_dict for every block variant: plain GQA,
    Qwen2 biases, Gemma-2 post-norms + tied head."""
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict
    from dnn_tpu.io.torch_export import llama_state_dict_from_params
    from dnn_tpu.models import llama

    for name in ("llama-test", "qwen2-test", "gemma2-test"):
        cfg = llama.PRESETS[name]
        params = llama.init(jax.random.PRNGKey(3), cfg)
        sd = llama_state_dict_from_params(params)
        if cfg.tie_word_embeddings:
            assert "lm_head.weight" not in sd, name
        if cfg.attn_bias:
            assert "model.layers.0.self_attn.q_proj.bias" in sd, name
        back = llama_params_from_state_dict(
            sd, n_layer=cfg.n_layer, post_norms=cfg.post_norms,
            tied_head="omit" if cfg.tie_word_embeddings
            else "materialize")
        _tree_equal(params, back)


def test_llama_family_export_loads_into_transformers(tmp_path):
    """The fine-tune-and-hand-back loop: export framework params to a
    .pth, torch.load into the matching HF class, logits must agree —
    including the Gemma-2 tied head (HF reties in-place on load) and
    Qwen2 biases."""
    import transformers

    from dnn_tpu.io.torch_export import (
        llama_state_dict_from_params,
        save_pth,
    )
    from dnn_tpu.models import gpt as _gpt  # noqa: F401 (family helpers)
    from dnn_tpu.models import llama

    for name, cls_name in (("qwen2-test", "Qwen2ForCausalLM"),
                           ("gemma2-test", "Gemma2ForCausalLM")):
        cfg = llama.PRESETS[name]
        params = llama.init(jax.random.PRNGKey(4), cfg)
        sd = llama_state_dict_from_params(params)
        path = str(tmp_path / f"{name}.pth")
        save_pth(path, sd)

        hf = getattr(transformers, cls_name)(
            llama.to_hf_config(cfg, attn_implementation="eager")).eval()
        missing, unexpected = hf.load_state_dict(
            torch.load(path, map_location="cpu", weights_only=True),
            strict=False)
        assert not unexpected, (name, unexpected)
        # tied models may report lm_head missing; it shares the
        # embedding's storage, which the load just overwrote in place
        assert all("lm_head" in m or "rotary" in m for m in missing), \
            (name, missing)

        ids = np.random.RandomState(5).randint(0, cfg.vocab_size, (2, 12))
        ours = np.asarray(llama.make_apply(cfg)(params,
                                                ids.astype(np.int32)))
        with torch.no_grad():
            theirs = hf(torch.from_numpy(ids)).logits.numpy()
        np.testing.assert_allclose(ours, theirs, atol=3e-3, rtol=3e-3)
