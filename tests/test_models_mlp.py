"""MLP family — the worked "add a model family" example (README guide).

Verifies the adaptation path end to end: registration, partition-vs-full
parity at every supported part count, torch-layout converter parity, and a
full PipelineEngine run selected purely by config — the zero-code-change
promise the reference can't make (its adaptation guide requires editing
node.py's import + registry dict, readme.md:100-108)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.models.mlp import DEFAULT_WIDTHS, make_spec


@pytest.fixture(scope="module")
def mlp_setup():
    spec = get_model("mlp")
    params = spec.init(jax.random.PRNGKey(0))
    x = spec.example_input(batch_size=4, rng=jax.random.PRNGKey(1))
    return spec, params, x


def test_registered_and_forward(mlp_setup):
    spec, params, x = mlp_setup
    y = spec.apply(params, x)
    assert y.shape == (4, DEFAULT_WIDTHS[-1])
    np.testing.assert_allclose(np.asarray(y).sum(axis=1), np.ones(4), rtol=1e-5)
    assert spec.supported_parts == (1, 2, 3)


@pytest.mark.parametrize("num_parts", [1, 2, 3])
def test_partition_parity(mlp_setup, num_parts):
    spec, params, x = mlp_setup
    stages = spec.partition(num_parts)
    assert len(stages) == num_parts
    h = x
    for stage in stages:
        h = stage.apply(stage.slice_params(params), h)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(spec.apply(params, x)))


def test_param_keys_cover_model_exactly(mlp_setup):
    spec, params, _ = mlp_setup
    for n in spec.supported_parts:
        keys = [k for s in spec.partition(n) for k in s.param_keys]
        assert sorted(keys) == sorted(params)  # disjoint + complete


def test_custom_widths_spec():
    spec = make_spec(name="mlp_test_tiny", widths=(8, 16, 16, 16, 4))
    params = spec.init(jax.random.PRNGKey(0))
    x = spec.example_input(batch_size=2)
    assert spec.apply(params, x).shape == (2, 4)
    assert spec.supported_parts == (1, 2, 3, 4)
    assert get_model("mlp_test_tiny") is spec
    # 3-way split of 4 layers balances 1/2/1 or 2/1/1-style contiguous ranges.
    stages = spec.partition(3)
    h = x
    for s in stages:
        h = s.apply(s.slice_params(params), h)
    np.testing.assert_array_equal(np.asarray(h), np.asarray(spec.apply(params, x)))


def test_convert_state_dict_matches_torch():
    torch = pytest.importorskip("torch")
    spec = get_model("mlp")
    tmods = [
        torch.nn.Linear(DEFAULT_WIDTHS[i], DEFAULT_WIDTHS[i + 1])
        for i in range(len(DEFAULT_WIDTHS) - 1)
    ]
    sd = {}
    for i, m in enumerate(tmods):
        sd[f"fc{i}.weight"] = m.weight.detach().numpy()
        sd[f"fc{i}.bias"] = m.bias.detach().numpy()
    params = spec.convert_state_dict(sd)

    x = np.random.default_rng(0).standard_normal((3, DEFAULT_WIDTHS[0])).astype(np.float32)
    with torch.no_grad():
        h = torch.from_numpy(x)
        for i, m in enumerate(tmods):
            h = m(h)
            h = torch.relu(h) if i < len(tmods) - 1 else torch.softmax(h, dim=-1)
    ours = np.asarray(spec.apply(params, jnp.asarray(x)))
    np.testing.assert_allclose(ours, h.numpy(), rtol=1e-5, atol=1e-6)


def test_engine_by_config(tmp_path):
    """Selecting the family is one config key — no framework edits."""
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict(
        {
            "model": "mlp",
            "num_parts": 3,
            "nodes": [
                {"id": f"n{i}", "address": f"127.0.0.1:{6000 + i}", "part_index": i}
                for i in range(3)
            ],
        }
    )
    eng = PipelineEngine(cfg)
    x = eng.spec.example_input(batch_size=2)
    y = np.asarray(eng.run(x))
    ref = np.asarray(eng.spec.apply(eng.params, x))
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)
