"""Wire codec + gRPC edge: serialization round-trips and a real 2-node
pipeline over localhost gRPC (in-process servers), exercising the full
reference deployment shape — SendTensor relay, response-chain result,
HealthCheck, SendMessage."""

import numpy as np
import pytest

from dnn_tpu.config import TopologyConfig
from dnn_tpu.io.serialization import decode_tensor, encode_tensor


# ----------------------------------------------------------------------
# codec
# ----------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float16", "int32", "int8", "bool"])
def test_codec_roundtrip(dtype):
    rng = np.random.default_rng(0)
    arr = (rng.standard_normal((3, 4, 5)) * 10).astype(dtype)
    data, shape, name = encode_tensor(arr)
    out = decode_tensor(data, shape, name)
    np.testing.assert_array_equal(out, arr)
    assert out.dtype == arr.dtype


def test_codec_bfloat16():
    import ml_dtypes

    arr = np.arange(6, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 3)
    data, shape, name = encode_tensor(arr)
    assert name == "bfloat16"
    np.testing.assert_array_equal(decode_tensor(data, shape, name), arr)


def test_codec_rejects_bad_length():
    data, shape, name = encode_tensor(np.zeros((2, 2), np.float32))
    with pytest.raises(ValueError, match="bytes"):
        decode_tensor(data[:-1], shape, name)
    with pytest.raises(ValueError, match="bytes"):
        decode_tensor(data, (2, 3), name)


def test_codec_scalar():
    data, shape, name = encode_tensor(np.float32(3.5))
    out = decode_tensor(data, shape, name)
    assert out.shape == () and float(out) == 3.5


# ----------------------------------------------------------------------
# gRPC edge pipeline (2 in-process stage servers on localhost)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def grpc_pipeline():
    import jax

    from dnn_tpu.comm.service import start_stage_server_in_background
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict(
        {
            "nodes": [
                {"id": "node1", "address": "127.0.0.1:59251", "part_index": 0},
                {"id": "node2", "address": "127.0.0.1:59252", "part_index": 1},
            ],
            "num_parts": 2,
            "model": "cifar_cnn",
            "runtime": "relay",
        }
    )
    engine = PipelineEngine(cfg)  # random init; both "hosts" share weights
    t1, stop1 = start_stage_server_in_background(engine, "node1")
    t2, stop2 = start_stage_server_in_background(engine, "node2")
    yield cfg, engine
    stop1()
    stop2()


def test_health_and_message(grpc_pipeline):
    from dnn_tpu.comm.client import NodeClient

    cfg, _ = grpc_pipeline
    c = NodeClient(cfg.node_by_id("node2").address)
    assert c.health_check()
    reply = c.send_message("node1", "hello")
    assert "node2" in reply and "hello" in reply
    c.close()


def test_sendtensor_relay_chain(grpc_pipeline):
    """Submit the stage-0 activation to node1: it must run its part, relay
    to node2 over gRPC, and return node2's softmax output up the response
    chain — the full node.py:35-105 behavior."""
    from dnn_tpu.comm.client import NodeClient

    cfg, engine = grpc_pipeline
    x = np.asarray(engine.spec.example_input(batch_size=1))

    c = NodeClient(cfg.node_by_id("node1").address)
    status, result = c.send_tensor(x, request_id="test_req_1")
    c.close()

    assert "Prediction" in status or "Forwarded" in status
    assert result is not None and result.shape == (1, 10)
    expect = np.asarray(engine.run(x))
    np.testing.assert_allclose(result, expect, atol=1e-5, rtol=1e-5)


def test_health_check_dead_endpoint():
    from dnn_tpu.comm.client import NodeClient

    c = NodeClient("127.0.0.1:59999")  # nothing listening
    assert c.health_check(timeout=0.5) is False
    c.close()


# ----------------------------------------------------------------------
# failure handling: bounded retries + health probing (SURVEY §5 mandate)
# ----------------------------------------------------------------------

def test_send_tensor_no_retry_raises_immediately():
    import time

    import grpc

    from dnn_tpu.comm.client import NodeClient

    c = NodeClient("127.0.0.1:59998")  # nothing listening -> UNAVAILABLE
    t0 = time.monotonic()
    with pytest.raises(grpc.RpcError):
        c.send_tensor(np.zeros((1, 4), np.float32), timeout=0.5, retries=0)
    assert time.monotonic() - t0 < 5.0
    c.close()


def test_send_tensor_retries_until_server_appears(grpc_pipeline):
    """Kill nothing — instead dial a not-yet-listening port, start a real
    server mid-retry, and check the request eventually lands (elastic
    startup ordering, which the reference handles with a blind sleep)."""
    import threading
    import time

    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.comm.service import start_stage_server_in_background
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict(
        {
            "nodes": [
                {"id": "late1", "address": "127.0.0.1:59261", "part_index": 0},
                # reuse the module fixture's node2 as downstream so the chain
                # completes
                {"id": "node2", "address": "127.0.0.1:59252", "part_index": 1},
            ],
            "num_parts": 2,
            "model": "cifar_cnn",
            "runtime": "relay",
        }
    )
    engine = PipelineEngine(cfg)
    holder = {}

    def start_late():
        time.sleep(0.7)
        holder["stop"] = start_stage_server_in_background(engine, "late1")[1]

    threading.Thread(target=start_late, daemon=True).start()
    c = NodeClient("127.0.0.1:59261")
    try:
        x = np.asarray(engine.spec.example_input(batch_size=1))
        status, result = c.send_tensor(
            x, timeout=10.0, retries=6, backoff=0.25
        )
        assert result is not None and result.shape == (1, 10)
    finally:
        c.close()
        if "stop" in holder:
            holder["stop"]()


def test_corrupt_request_fails_rpc_with_data_loss(grpc_pipeline):
    """A corrupt payload must fail the RPC with DATA_LOSS (so senders
    retry), not come back as a status-string 'success'."""
    import grpc

    from dnn_tpu.native import native_available

    if not native_available():
        pytest.skip("crc verification requires the native codec")

    from dnn_tpu.comm import wire_pb2 as pb
    from dnn_tpu.comm.service import SERVICE_NAME, _tensor_msg

    cfg, engine = grpc_pipeline
    x = np.asarray(engine.spec.example_input(batch_size=1))
    msg = _tensor_msg(x)
    data = bytearray(msg.tensor_data)
    data[3] ^= 0x10  # flip a bit, keep the declared crc
    bad = pb.Tensor(
        tensor_data=bytes(data), shape=msg.shape, dtype=msg.dtype,
        crc32c=msg.crc32c,
    )
    channel = grpc.insecure_channel(cfg.node_by_id("node1").address)
    call = channel.unary_unary(
        f"/{SERVICE_NAME}/SendTensor",
        request_serializer=pb.TensorRequest.SerializeToString,
        response_deserializer=pb.TensorResponse.FromString,
    )
    with pytest.raises(grpc.RpcError) as exc_info:
        call(pb.TensorRequest(request_id="corrupt", tensor=bad), timeout=10)
    assert exc_info.value.code() == grpc.StatusCode.DATA_LOSS
    channel.close()


def test_wait_healthy(grpc_pipeline):
    from dnn_tpu.comm.client import NodeClient

    cfg, _ = grpc_pipeline
    up = NodeClient(cfg.node_by_id("node1").address)
    assert up.wait_healthy(deadline=5.0) is True
    up.close()

    down = NodeClient("127.0.0.1:59997")
    assert down.wait_healthy(deadline=1.0, interval=0.2) is False
    down.close()
