"""1F1B pipeline-training schedule + auto microbatching.

Round-1 weak spot #5: GPipe only, and microbatches defaulted to 1 — the
out-of-the-box spmd runtime was semantically a serial relay with a
(S-1)/(M+S-1) bubble. Now make_pipeline_train_step(schedule="1f1b") runs
the fused one-forward-one-backward loop (activation stash bounded at
min(M, 2S-1) slots, not M), and the engine auto-picks microbatches > 1."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
from dnn_tpu.parallel.pipeline import split_microbatches, spmd_pipeline_train_1f1b

CFG = gpt.PRESETS["gpt2-test"]


def _setup(num_stages, seed=0):
    mesh = make_mesh({STAGE_AXIS: num_stages}, jax.devices()[:num_stages])
    params = gpt.init(jax.random.PRNGKey(seed), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    per = CFG.n_layer // num_stages
    stacked = jax.tree.map(
        lambda p: p.reshape(num_stages, per, *p.shape[1:]), prepared["blocks"]
    )
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    return mesh, stacked, aux


def _fns():
    return (
        lambda bp, h: gpt.blocks_scan(bp, h, cfg=CFG),
        lambda a, ids: gpt.embed(a, ids, cfg=CFG),
        lambda a, h: gpt.head(a, h.astype(jnp.float32), cfg=CFG),
    )


@pytest.mark.parametrize("num_stages,microbatches", [(2, 4), (4, 8), (4, 2)])
def test_1f1b_grads_match_single_device(num_stages, microbatches):
    mesh, stacked, aux = _setup(num_stages)
    block_fn, embed_fn, head_fn = _fns()
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (microbatches, 17), 0, CFG.vocab_size, jnp.int32
    )

    def sd_loss(stacked, aux):
        flat = jax.tree.map(lambda p: p.reshape(CFG.n_layer, *p.shape[2:]), stacked)
        h = gpt.blocks_scan(flat, embed_fn(aux, tokens[:, :-1]), cfg=CFG)
        return train.cross_entropy(head_fn(aux, h), tokens[:, 1:])

    l_sd, (g_st_sd, g_aux_sd) = jax.value_and_grad(sd_loss, argnums=(0, 1))(
        stacked, aux
    )

    l_fb, g_st_fb, g_aux_fb = spmd_pipeline_train_1f1b(
        block_fn, embed_fn,
        lambda ax, h, t: train.cross_entropy(head_fn(ax, h), t),
        stacked, aux,
        split_microbatches(tokens[:, :-1], microbatches),
        split_microbatches(tokens[:, 1:], microbatches),
        mesh=mesh,
    )
    np.testing.assert_allclose(float(l_fb), float(l_sd), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g_st_fb), jax.tree.leaves(g_st_sd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6, rtol=1e-4)
    for a, b in zip(jax.tree.leaves(g_aux_fb), jax.tree.leaves(g_aux_sd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-6, rtol=1e-4)


def test_1f1b_train_step_parity_with_gpipe():
    mesh, stacked, aux = _setup(4)
    block_fn, embed_fn, head_fn = _fns()
    opt = optax.sgd(1e-2)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (8, 17), 0, CFG.vocab_size,
                                jnp.int32)
    outs = {}
    for sched in ("gpipe", "1f1b"):
        step = train.make_pipeline_train_step(
            block_fn, embed_fn, head_fn, opt, mesh,
            num_microbatches=8, schedule=sched,
        )
        st, ax, _, loss = step(
            stacked, aux, (opt.init(stacked), opt.init(aux)), tokens
        )
        outs[sched] = (float(loss), st, ax)
    assert outs["gpipe"][0] == pytest.approx(outs["1f1b"][0], rel=1e-6)
    for a, b in zip(jax.tree.leaves(outs["gpipe"][1]), jax.tree.leaves(outs["1f1b"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(outs["gpipe"][2]), jax.tree.leaves(outs["1f1b"][2])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-7, rtol=1e-5)


def test_1f1b_uses_less_activation_memory_than_gpipe():
    """The schedule's point: at M >> S, compiled temp memory (which holds
    the live activations) must be well below GPipe's."""
    cfg = gpt.GPTConfig(block_size=128, vocab_size=128, n_layer=2, n_head=2,
                        n_embd=64)
    S, M = 2, 16
    mesh = make_mesh({STAGE_AXIS: S}, jax.devices()[:S])
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    stacked = jax.tree.map(lambda p: p.reshape(S, 1, *p.shape[1:]),
                           prepared["blocks"])
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    opt = optax.sgd(1e-2)
    tokens = jnp.zeros((16, 129), jnp.int32)

    temp = {}
    for sched in ("gpipe", "1f1b"):
        step = train.make_pipeline_train_step(
            lambda bp, h: gpt.blocks_scan(bp, h, cfg=cfg),
            lambda a, ids: gpt.embed(a, ids, cfg=cfg),
            lambda a, h: gpt.head(a, h.astype(jnp.float32), cfg=cfg),
            opt, mesh, num_microbatches=M, schedule=sched,
        )
        ma = step.lower(
            stacked, aux, (opt.init(stacked), opt.init(aux)), tokens
        ).compile().memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend does not report memory analysis")
        temp[sched] = ma.temp_size_in_bytes
    assert temp["1f1b"] < temp["gpipe"] / 3, temp


def test_make_pipeline_train_step_rejects_bad_schedule():
    mesh, _, _ = _setup(2)
    with pytest.raises(ValueError, match="schedule"):
        train.make_pipeline_train_step(*_fns(), optax.sgd(1e-2), mesh,
                                       schedule="pipedream")


def test_engine_auto_microbatches():
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict({
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
        "num_parts": 4,
        "model": "gpt2-test",
        "device_type": "cpu",
        "runtime": "spmd",
    })
    assert cfg.microbatches == 0  # default is now auto
    eng = PipelineEngine(cfg, rng_seed=0)
    # batch 8, 4 parts -> auto picks 2*parts = 8 microbatches
    assert eng._effective_microbatches(8) == 8
    assert eng._effective_microbatches(6) == 6
    assert eng._effective_microbatches(7) == 7  # divisor of 7 <= 8
    assert eng._effective_microbatches(1) == 1
    assert eng._effective_microbatches(32) == 8  # capped at 2*parts
    # explicit config value passes through untouched
    cfg2 = TopologyConfig.from_dict({
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
        "num_parts": 4, "model": "gpt2-test", "device_type": "cpu",
        "runtime": "spmd", "microbatches": 2,
    })
    assert PipelineEngine(cfg2, rng_seed=0)._effective_microbatches(8) == 2

    # and the auto path must still match the full model numerically
    ids = eng.spec.example_input(batch_size=8, seq_len=16)
    np.testing.assert_allclose(
        np.asarray(eng.run(ids)),
        np.asarray(eng.spec.apply(eng.params, ids)),
        atol=1e-4, rtol=1e-4,
    )
