"""FLOPs accounting / MFU tests (the bench harness's analytic side)."""

import jax
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.utils import flops


def test_gpt_forward_flops_scales():
    cfg = gpt.PRESETS["gpt2"]
    base = flops.gpt_forward_flops(cfg, 1, 512)
    assert flops.gpt_forward_flops(cfg, 4, 512) == 4 * base
    # doubling seq more than doubles (attention T^2 term)
    assert flops.gpt_forward_flops(cfg, 1, 1024) > 2 * base
    # gpt2-small at T=512: ~0.25 GFLOP/token is the well-known ballpark
    per_token = base / 512
    assert 2e8 < per_token < 4e8, per_token


def test_gpt_train_flops_is_3x_forward():
    cfg = gpt.PRESETS["gpt2-test"]
    assert flops.gpt_train_step_flops(cfg, 2, 32) == \
        3 * flops.gpt_forward_flops(cfg, 2, 32)


def test_train_step_factor_goldens():
    # hand-computed factors: 3x forward plain, 4x under remat (the
    # backward replays the forward); microbatch accumulation leaves
    # the TOTAL unchanged (forward FLOPs are linear in batch)
    cfg = gpt.PRESETS["gpt2-test"]
    fwd = flops.gpt_forward_flops(cfg, 8, 32)
    assert flops.gpt_train_step_flops(cfg, 8, 32, remat=True) == 4 * fwd
    assert flops.gpt_train_step_flops(cfg, 8, 32, accum_steps=4) == \
        3 * fwd
    # the divisibility check mirrors make_train_step's own rejection
    with pytest.raises(ValueError):
        flops.gpt_train_step_flops(cfg, 8, 32, accum_steps=3)
    with pytest.raises(ValueError):
        flops.gpt_train_step_flops(cfg, 8, 32, accum_steps=0)

    from dnn_tpu.models import llama

    lcfg = llama.PRESETS["tinyllama-1.1b"]
    lfwd = flops.llama_forward_flops(lcfg, 2, 64)
    assert flops.llama_train_step_flops(lcfg, 2, 64) == 3 * lfwd
    assert flops.llama_train_step_flops(lcfg, 2, 64, remat=True) == \
        4 * lfwd


def test_goodput_train_step_flops_delegates_per_family():
    # one analytic walk: the serving-side helper must sniff the config
    # family and agree exactly with the utils/flops owners
    from dnn_tpu.models import llama
    from dnn_tpu.obs.goodput import train_step_flops

    gcfg = gpt.PRESETS["gpt2-test"]
    assert train_step_flops(gcfg, 4, 32) == \
        flops.gpt_train_step_flops(gcfg, 4, 32)
    lcfg = llama.PRESETS["tinyllama-1.1b"]
    assert train_step_flops(lcfg, 2, 64, remat=True) == \
        flops.llama_train_step_flops(lcfg, 2, 64, remat=True)


def test_cifar_forward_flops_ballpark():
    per_image = flops.cifar_forward_flops(1)
    assert 1e7 < per_image < 3e7, per_image  # ~15.4 MFLOP/image


def test_device_peak_and_mfu_off_tpu():
    dev = jax.devices()[0]
    if dev.platform == "tpu":
        pytest.skip("suite runs on the CPU mesh")
    assert flops.device_peak_flops(dev) is None
    assert flops.mfu(1e9, 1000.0, dev) is None


def test_peak_table_matching():
    class FakeDev:
        platform = "tpu"

        def __init__(self, kind):
            self.device_kind = kind

    assert flops.device_peak_flops(FakeDev("TPU v5 lite")) == 197e12
    assert flops.device_peak_flops(FakeDev("TPU v4")) == 275e12
    assert flops.device_peak_flops(FakeDev("TPU v5p")) == 459e12
    assert flops.device_peak_flops(FakeDev("TPU weird-future")) is None
    # mfu math: 100 items/s at 1e12 FLOPs/item on a 197e12 chip
    assert flops.mfu(1e12, 100.0, FakeDev("TPU v5e")) == pytest.approx(
        100e12 / 197e12
    )


def test_hbm_and_roofline_accounting():
    from dnn_tpu.utils.flops import (
        cifar_forward_bytes, cifar_forward_flops, device_peak_hbm_bw, mbu,
        roofline_items_per_sec,
    )

    # per-image activation traffic dominates; weights amortize over batch
    b1, b2, b256 = (cifar_forward_bytes(n) for n in (1, 2, 256))
    assert b256 < 256 * b1  # weights counted once per batch
    weights = 2 * b1 - b2   # bytes(n) = n*act + weights
    per_img = (b256 - weights) / 256
    assert 2e5 < per_img < 4e5  # ~0.27 MB/image in bf16
    # arithmetic intensity sits far below any TPU ridge point
    intensity = cifar_forward_flops(1) / per_img
    assert 30 < intensity < 120
    # CPU host: no peak tables -> None, callers omit the fields
    assert device_peak_hbm_bw() is None
    assert mbu(1e6, 1e6) is None
    assert roofline_items_per_sec(1e6, 1e5) is None


def test_llama_flops_accounting():
    from dnn_tpu.models import llama
    from dnn_tpu.utils.flops import llama_forward_flops

    cfg = llama.PRESETS["tinyllama-1.1b"]
    # per-token cost ~ 2 * N_params + attention: TinyLlama has ~1.1B
    # params, so the linear part sits near 2.2 GFLOPs/token
    per_tok = llama_forward_flops(cfg, 1, 512) / 512
    assert 2.0e9 < per_tok < 3.5e9, per_tok
    # GQA narrows only the k/v projections: an MHA twin costs more
    import dataclasses

    mha = dataclasses.replace(cfg, n_kv_head=cfg.n_head)
    assert llama_forward_flops(mha, 1, 512) > llama_forward_flops(cfg, 1, 512)
