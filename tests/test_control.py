"""dnn_tpu.control — fleet front door: policies, replica lifecycle,
KV handoff, and the router end to end.

The e2e legs run REAL gRPC through an in-process router over
in-process LM servers (start_lm_server_in_background) — the same wire
path `node --route` serves, minus subprocesses (the fleet probe and
`python -m dnn_tpu.control` own the real-subprocess shape). Policy,
admission, autoscaling and protocol checks are pure host goldens with
injected signals."""

import threading
import time

import jax
import numpy as np
import pytest

from dnn_tpu.control import handoff
from dnn_tpu.control.policy import (
    POLICIES,
    ReplicaView,
    get_policy,
    shed_reason,
    wanted_replicas,
)
from dnn_tpu.models import gpt

CFG = gpt.PRESETS["gpt2-test"]

# distinct from every other module's port ranges
_PORTS = iter(range(59730, 59790))


def _prompt(n=8):
    return (np.arange(1, n + 1) % CFG.vocab_size).astype(np.int32)


@pytest.fixture(scope="module")
def prepared():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    return gpt.prepare_stacked(params, CFG)


# ----------------------------------------------------------------------
# policies (pure goldens, injected signals)
# ----------------------------------------------------------------------

def _v(name, **kw):
    return ReplicaView(name=name, **kw)


def test_round_robin_cycles_by_name():
    p = get_policy("round_robin")
    cands = [_v("b"), _v("a"), _v("c")]
    picks = [p.pick(cands).name for _ in range(6)]
    assert picks == ["a", "b", "c", "a", "b", "c"]


def test_least_queue_golden_and_inflight_fallback():
    p = get_policy("least_queue")
    # scraped queue depth dominates
    assert p.pick([_v("a", queue_depth=5), _v("b", queue_depth=1)]
                  ).name == "b"
    # local inflight covers the scrape lag (and is the whole signal
    # when scraping is off)
    assert p.pick([_v("a", queue_depth=1, inflight=4),
                   _v("b", queue_depth=2, inflight=0)]).name == "b"
    assert p.pick([_v("a", inflight=3), _v("b", inflight=1)]).name == "b"


def test_slo_burn_golden_burn_dominates_queue():
    p = get_policy("slo_burn")
    # replica a: empty queue but burning budget at 2x; replica b: a few
    # queued requests, quiet burn -> b wins (burn outranks ~8 queued)
    a = _v("a", queue_depth=0, burn={"ttft": 2.0})
    b = _v("b", queue_depth=4, burn={"ttft": 0.1})
    assert p.pick([a, b]).name == "b"
    # with burns equal, load decides; ttft p99 breaks the last tie
    assert p.pick([_v("a", queue_depth=3), _v("b", queue_depth=1)]
                  ).name == "b"
    assert p.pick([_v("a", ttft_p99_ms=500.0), _v("b", ttft_p99_ms=5.0)]
                  ).name == "b"


def test_policy_registry():
    assert set(POLICIES) == {"round_robin", "least_queue", "slo_burn"}
    with pytest.raises(ValueError, match="unknown routing policy"):
        get_policy("fastest")


def test_shed_reason_golden():
    assert shed_reason([], max_inflight=4) == "no_serving_replica"
    sat = [_v("a", inflight=4), _v("b", inflight=9)]
    assert shed_reason(sat, max_inflight=4) == "saturated"
    ok = [_v("a", inflight=4), _v("b", inflight=1)]
    assert shed_reason(ok, max_inflight=4) is None
    burning = [_v("a", burn={"availability": 3.0}),
               _v("b", burn={"ttft": 1.5})]
    assert shed_reason(burning, max_inflight=4, shed_burn=1.0) \
        == "slo_burn"
    # one quiet candidate admits
    assert shed_reason(burning + [_v("c", burn={"ttft": 0.2})],
                       max_inflight=4, shed_burn=1.0) is None
    # burn gate off by default
    assert shed_reason(burning, max_inflight=4) is None


def test_wanted_replicas_arithmetic():
    # pressure ~1: hold
    calm = [_v("a", state="serving", queue_depth=2),
            _v("b", state="serving", queue_depth=2)]
    assert wanted_replicas(calm, slots_hint=4) == 2
    # queue 3x capacity: scale toward pressure 1
    hot = [_v("a", state="serving", queue_depth=12, inflight=0),
           _v("b", state="serving", queue_depth=12, inflight=0)]
    assert wanted_replicas(hot, slots_hint=4) == 6
    # burn >= 1 adds one even with short queues
    burn = [_v("a", state="serving", queue_depth=0,
               burn={"ttft": 1.4})]
    assert wanted_replicas(burn, slots_hint=4) == 2
    # idle fleet gives one back, never below 1
    idle = [_v("a", state="serving", queue_depth=0),
            _v("b", state="serving", queue_depth=0)]
    assert wanted_replicas(idle, slots_hint=4) == 1
    assert wanted_replicas([_v("a", state="serving", queue_depth=0)],
                           slots_hint=4) == 1
    # only SERVING replicas count
    assert wanted_replicas([_v("a", state="dead")]) == 1
    # ACTIVE SHEDDING wants one more whatever the queues say: admission
    # control keeps replica queues short precisely when demand exceeds
    # the fleet — queue depth alone is blind to shed pressure
    assert wanted_replicas(idle, slots_hint=4, shedding=True) == 3
    assert wanted_replicas(calm, slots_hint=4, shedding=True) == 3


# ----------------------------------------------------------------------
# protocol tables (model check both directions + buggy fixtures)
# ----------------------------------------------------------------------

def test_control_machines_registered_and_clean():
    import dataclasses

    from dnn_tpu.analysis.protocol import (
        MACHINES,
        REPLICA,
        ROUTER,
        check_machine,
        check_machine_sites,
    )

    assert REPLICA in MACHINES and ROUTER in MACHINES
    for m in (REPLICA, ROUTER):
        assert check_machine(m) == []
        assert check_machine_sites(m, ".") == []
    # drop the respawn edge: dead becomes absorbing -> the "fleet
    # shrinks forever" bug reproduces as a PRO002 model failure
    buggy = dataclasses.replace(
        REPLICA, edges=tuple(e for e in REPLICA.edges
                             if e.event != "replica_respawn"))
    rules = {f.rule for f in check_machine(buggy)}
    assert "PRO002" in rules


def test_router_fixture_flagged_by_site_check():
    from dnn_tpu.analysis.protocol import ROUTER, check_machine_sites

    # a Router that invents an undeclared state and records an event no
    # edge declares: both directions must flag
    src = (
        "from dnn_tpu.obs import flight\n"
        "class Router:\n"
        "    def __init__(self):\n"
        "        self._state = 'init'\n"
        "    def start(self):\n"
        "        self._state = 'serving'\n"
        "        flight.record('router_start')\n"
        "    def explode(self):\n"
        "        self._state = 'on_fire'\n"
        "        flight.record('router_meltdown')\n")
    found = check_machine_sites(ROUTER, ".", src=src)
    rules = [f.rule for f in found]
    assert "PRO003" in rules  # undeclared state + unmapped event
    assert "PRO004" in rules  # declared edges with no site in fixture


# ----------------------------------------------------------------------
# KV handoff: wire format + batcher-level export/adopt parity
# ----------------------------------------------------------------------

def test_handoff_pack_roundtrip_including_bf16():
    import ml_dtypes

    payload = {
        "row": [np.arange(24, dtype=np.float32).reshape(2, 3, 4),
                np.arange(6, dtype=np.int8).reshape(2, 3),
                np.ones((2, 2), ml_dtypes.bfloat16)],
        "logits_row": np.linspace(0, 1, 7, dtype=np.float32),
        "prompt_len": 5,
        "fingerprint": {"vocab_size": 7, "row_len": 4},
    }
    buf = handoff.pack(payload)
    assert buf.dtype == np.uint8 and buf.ndim == 1
    back = handoff.unpack(buf)
    assert back["prompt_len"] == 5
    assert back["fingerprint"] == payload["fingerprint"]
    for a, b in zip(payload["row"], back["row"]):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(payload["logits_row"],
                                  back["logits_row"])


def test_handoff_malformed_payloads_fail_loud():
    buf = handoff.pack({"row": [np.zeros((2,), np.float32)],
                        "logits_row": np.zeros((3,), np.float32),
                        "prompt_len": 1, "fingerprint": {}})
    with pytest.raises(ValueError, match="bad magic"):
        handoff.unpack(np.zeros(16, np.uint8))
    with pytest.raises(ValueError, match="truncated"):
        handoff.unpack(buf[: buf.size - 4])


def test_export_adopt_parity_and_rejections(prepared):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    kw = dict(slots=2, max_len=CFG.block_size, prompt_pad=16)
    prompt = _prompt(9)
    pre = ContinuousBatcher(CFG, prepared, **kw)
    pay = handoff.unpack(handoff.pack(pre.export_prefill(prompt)))
    # greedy parity vs a locally-prefilled pool
    dec = ContinuousBatcher(CFG, prepared, **kw)
    rid = dec.submit(prompt, 8, prefilled=pay)
    got = dec.drain()[rid]
    ref = ContinuousBatcher(CFG, prepared, **kw)
    rid = ref.submit(prompt, 8)
    want = ref.drain()[rid]
    np.testing.assert_array_equal(got, want)
    # sampled parity, draw-for-draw (same seed -> same rng derivation)
    skw = dict(kw, temperature=0.8, top_k=32)
    pre_s = ContinuousBatcher(CFG, prepared, **skw)
    pay_s = handoff.unpack(handoff.pack(pre_s.export_prefill(prompt)))
    dec_s = ContinuousBatcher(CFG, prepared, **skw)
    rid = dec_s.submit(prompt, 8, seed=7, prefilled=pay_s)
    got_s = dec_s.drain()[rid]
    ref_s = ContinuousBatcher(CFG, prepared, **skw)
    rid = ref_s.submit(prompt, 8, seed=7)
    np.testing.assert_array_equal(got_s, ref_s.drain()[rid])
    # PAGED pool adopts the same dense row (install_row routes it into
    # the pool blocks the admission allocated)
    pg = ContinuousBatcher(CFG, prepared, kv="paged", **kw)
    rid = pg.submit(prompt, 8, prefilled=pay)
    np.testing.assert_array_equal(pg.drain()[rid], want)
    # geometry mismatch fails loud at admission
    other = ContinuousBatcher(CFG, prepared, slots=2, max_len=32,
                              prompt_pad=8)
    with pytest.raises(ValueError, match="must share model config"):
        other.submit(_prompt(5), 4, prefilled=pay)
    # interleaved admission rejects adoption
    ilv = ContinuousBatcher(CFG, prepared, prefill_chunk_tokens=16, **kw)
    with pytest.raises(ValueError, match="prefill_chunk_tokens"):
        ilv.submit(prompt, 4, prefilled=pay)
    # fingerprints match between same-geometry pools, differ otherwise
    assert pre.handoff_fingerprint() == dec.handoff_fingerprint()
    assert pre.handoff_fingerprint() != other.handoff_fingerprint()


# ----------------------------------------------------------------------
# Supervisor: injectable ready-probe endpoint/port (satellite bugfix)
# ----------------------------------------------------------------------

def test_supervisor_health_endpoint_injectable():
    import http.server
    import subprocess
    import sys

    from dnn_tpu.chaos.supervisor import Supervisor

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            ok = self.path == "/replicaz"
            self.send_response(200 if ok else 404)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"ok")

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    port = srv.server_address[1]
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        spawn = lambda: subprocess.Popen(  # noqa: E731
            [sys.executable, "-c", "import time; time.sleep(30)"])
        # CALLABLE url resolved per poll + custom path — the fleet
        # spawner's shape: distinct metrics ports, no subclassing
        sup = Supervisor(spawn, name="probe-test",
                         health_url=lambda: f"http://127.0.0.1:{port}",
                         health_path="/replicaz")
        try:
            sup.proc = spawn()
            assert sup._healthy_once() is True
            sup.health_path = "/healthz"  # the old fixed path 404s here
            assert sup._healthy_once() is False
            # a callable that cannot resolve yet reads not-healthy
            sup.health_url = lambda: None
            assert sup._healthy_once() is False
        finally:
            if sup.proc is not None:
                sup.proc.kill()
                sup.proc.wait(timeout=10)
    finally:
        srv.shutdown()
        srv.server_close()


# ----------------------------------------------------------------------
# router e2e over real gRPC (in-process replicas)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def fleet(prepared):
    """Two in-process LM replicas + an attach-mode ReplicaSet + router.
    Torn down at module end; the drain test (LAST in this file) drains
    replica r0 and leaves it drained."""
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import start_router_in_background
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    p1, p2, pr = next(_PORTS), next(_PORTS), next(_PORTS)
    _t1, stop1 = start_lm_server_in_background(
        CFG, prepared, port=p1, slots=2, seed=0, kv="dense")
    _t2, stop2 = start_lm_server_in_background(
        CFG, prepared, port=p2, slots=2, seed=0, kv="dense")
    rset = ReplicaSet(
        [ReplicaHandle("r0", f"127.0.0.1:{p1}"),
         ReplicaHandle("r1", f"127.0.0.1:{p2}")],
        interval_s=0.3).start()
    assert rset.wait_serving(2, 60)
    router, rstop = start_router_in_background(
        rset, port=pr, policy="round_robin")
    yield {"router_port": pr, "p1": p1, "p2": p2, "rset": rset,
           "router": router, "stops": (stop1, stop2),
           "servers": (stop1.servicer, stop2.servicer)}
    rstop()
    rset.stop()
    stop1()
    stop2()


@pytest.fixture()
def client(fleet):
    from dnn_tpu.comm.client import NodeClient

    c = NodeClient(f"127.0.0.1:{fleet['router_port']}", transport="grpc")
    yield c
    c.close()


def test_router_roundtrip_matches_direct(fleet, client):
    from dnn_tpu.comm.client import NodeClient

    prompt = _prompt()
    got = client.generate(prompt, max_new_tokens=8, seed=3)
    direct = NodeClient(f"127.0.0.1:{fleet['p1']}", transport="grpc")
    try:
        want = direct.generate(prompt, max_new_tokens=8, seed=3)
    finally:
        direct.close()
    np.testing.assert_array_equal(got, want)


def test_router_spreads_load_round_robin(fleet, client):
    s1, s2 = fleet["servers"]
    before = (s1.batcher._next_rid, s2.batcher._next_rid)
    for i in range(4):
        client.generate(_prompt(), max_new_tokens=3, seed=i)
    d1 = s1.batcher._next_rid - before[0]
    d2 = s2.batcher._next_rid - before[1]
    assert d1 + d2 == 4 and d1 == d2 == 2, (d1, d2)


def test_router_affinity_and_dedup_join(fleet, client):
    s1, s2 = fleet["servers"]
    before = s1.batcher._next_rid + s2.batcher._next_rid
    a = client.generate(_prompt(), max_new_tokens=6, seed=5,
                        dedup="ctrl-key-1")
    b = client.generate(_prompt(), max_new_tokens=6, seed=5,
                        dedup="ctrl-key-1")
    np.testing.assert_array_equal(a, b)
    # affinity landed both on ONE replica, where the second JOINED the
    # first's future — exactly one admission total
    after = s1.batcher._next_rid + s2.batcher._next_rid
    assert after - before == 1, (before, after)


def test_router_streaming_passthrough(fleet, client):
    toks = list(client.generate_stream(_prompt(), max_new_tokens=5,
                                       seed=2))
    assert len(toks) == 5
    want = client.generate(_prompt(), max_new_tokens=5, seed=2)
    np.testing.assert_array_equal(np.asarray(toks, np.int32), want)


def test_router_disagg_parity_and_zero_decode_prefill(fleet, prepared):
    """Same two servers attached under prefill/decode roles: the gen
    path runs the handoff (prefill replica computes the KV, decode
    replica adopts) and tokens match the role=both route exactly."""
    from dnn_tpu import obs
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import start_router_in_background

    s1, s2 = fleet["servers"]
    pr = next(_PORTS)
    rset = ReplicaSet(
        [ReplicaHandle("pre", f"127.0.0.1:{fleet['p1']}",
                       role="prefill"),
         ReplicaHandle("dec", f"127.0.0.1:{fleet['p2']}",
                       role="decode")],
        interval_s=0.3).start()
    assert rset.wait_serving(2, 30)
    _router, rstop = start_router_in_background(rset, port=pr)
    c = NodeClient(f"127.0.0.1:{pr}", transport="grpc")
    try:
        chunks_before = s2.batcher.prefill_chunks_run
        prompt = _prompt(19)
        got = c.generate(prompt, max_new_tokens=8, seed=4)
        # reference: the same request through the plain (role=both)
        # router of the module fixture
        ref = NodeClient(f"127.0.0.1:{fleet['router_port']}",
                         transport="grpc")
        try:
            want = ref.generate(prompt, max_new_tokens=8, seed=4)
        finally:
            ref.close()
        np.testing.assert_array_equal(got, want)
        # the decode replica adopted — it ran ZERO new prefill chunks
        assert s2.batcher.prefill_chunks_run == chunks_before
        assert obs.flight.recorder().events(kind="kv_handoff")
    finally:
        c.close()
        rstop()
        rset.stop()


def test_router_budget_and_disagg_decision_units():
    """`dl=` budgets are trusted AS-IS (never clamped down to the
    router default — the client already re-tags remaining budget per
    attempt), and the disagg decision skips `h=`/`a=`-tagged gens."""
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import Router

    def _rset():
        return ReplicaSet([ReplicaHandle("u0", "127.0.0.1:1")])

    r = Router(_rset(), default_deadline_s=30.0)
    assert r._budget("gen:4:1") == 30.0
    assert r._budget("gen:4:1:dl=120.000") == 120.0  # > default: kept
    assert r._budget("gen:4:1:dl=2.500") == 2.5      # < default: kept
    assert r._budget("gen:4:1:dl=0.000") == 0.001    # floored
    assert r._wants_disagg("gen:4:1")
    assert r._wants_disagg("gen:4:1:t=0.5:d=key")
    assert not r._wants_disagg("gen:4:1:h=abc")      # handle present
    assert not r._wants_disagg("gen:4:1:a=0")        # adapter: the
    # decode-side submit(prefilled=) adoption rejects adapters
    assert not r._wants_disagg("kvput:abc")
    assert not r._wants_disagg("embed:mean")
    r2 = Router(_rset(), disagg="off")
    assert not r2._wants_disagg("gen:4:1")


def test_router_kvput_then_generate_lands_on_staging_replica(
        fleet, client):
    """Client-driven kvput-then-generate through the router: the
    kvput forward must BIND `h=<key>` affinity so the follow-up
    generate re-routes to the replica actually holding the staged KV
    (unbound, round-robin would miss ~50% per request)."""
    ref = client.generate(_prompt(11), max_new_tokens=6, seed=7)
    for i in range(4):  # 4 fresh keys: P(pass unbound) = 1/16
        key = f"kvaff{i}"
        payload = client.prefill_kv(_prompt(11))
        client.put_kv(key, payload)
        status, result = client.send_tensor(
            _prompt(11), request_id=f"gen:6:7:h={key}",
            timeout=30.0, retries=0)
        assert result is not None, status
        np.testing.assert_array_equal(np.asarray(result, np.int32), ref)


def test_router_pinned_handoff_failure_falls_back_to_plain_rid():
    """The disagg generate leg failing on the pinned decode replica
    (adoption rejected / drain after put_kv) must retry siblings with
    the PLAIN rid — no sibling ever staged the router-minted handle —
    instead of surfacing INVALID_ARGUMENT for a valid request."""
    import asyncio

    import grpc

    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import Router

    h0, h1 = (ReplicaHandle("f0", "127.0.0.1:1"),
              ReplicaHandle("f1", "127.0.0.1:2"))
    h0.state = h1.state = "serving"
    router = Router(ReplicaSet([h0, h1]), policy="round_robin")

    class _Rpc(grpc.RpcError):
        def __init__(self, code):
            self._code = code

        def code(self):
            return self._code

        def details(self):
            return "adoption rejected"

    class _FakeClient:
        def __init__(self):
            self.rids = []

        def send_tensor(self, arr, *, request_id, timeout, retries):
            self.rids.append(request_id)
            if "h=" in request_id:
                raise _Rpc(grpc.StatusCode.INVALID_ARGUMENT)
            return "ok", np.arange(3, dtype=np.int32)

    fakes = {"f0": _FakeClient(), "f1": _FakeClient()}
    router._clients.update(fakes)

    class _Ctx:
        async def abort(self, code, details):
            raise AssertionError(f"aborted: {code} {details}")

    resp = asyncio.run(router._forward_unary(
        _prompt(), "gen:3:1:h=rt0", _Ctx(), pinned=h0,
        fallback_rid="gen:3:1"))
    assert resp.result_tensor is not None
    all_rids = fakes["f0"].rids + fakes["f1"].rids
    # exactly one handle-tagged attempt (the pinned one), then the
    # plain-rid fallback that succeeded
    assert [r for r in all_rids if "h=" in r] == ["gen:3:1:h=rt0"]
    assert "gen:3:1" in all_rids


def test_router_sheds_unavailable_when_no_replica(prepared):
    import grpc

    from dnn_tpu import obs
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.control.replicaset import ReplicaHandle, ReplicaSet
    from dnn_tpu.control.router import start_router_in_background

    pr, dead_port = next(_PORTS), next(_PORTS)
    rset = ReplicaSet(
        [ReplicaHandle("gone", f"127.0.0.1:{dead_port}")],
        interval_s=0.2).start()
    router, rstop = start_router_in_background(rset, port=pr)
    c = NodeClient(f"127.0.0.1:{pr}", transport="grpc")
    try:
        with pytest.raises(grpc.RpcError) as ei:
            c.send_tensor(_prompt(), request_id="gen:4:1", timeout=6.0,
                          retries=0)
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        assert "shedding" in (ei.value.details() or "")
        assert router.shed_total >= 1
        assert any(e["kind"] == "router_shed"
                   for e in obs.flight.recorder().events(
                       kind="router_shed"))
    finally:
        c.close()
        rstop()
        rset.stop()


def test_node_route_and_role_cli_validation(tmp_path):
    import json

    from dnn_tpu.node import main

    cfg = {"nodes": [{"id": "n0", "address": "127.0.0.1:59788",
                      "part_index": 0}],
           "num_parts": 1, "model": "gpt2-test", "device_type": "cpu"}
    path = tmp_path / "cfg.json"
    path.write_text(json.dumps(cfg))
    base = ["--node_id", "n0", "--config", str(path)]
    # --role needs --serve_lm
    assert main(base + ["--role", "prefill"]) == 1
    # --route needs --route_targets
    assert main(base + ["--route"]) == 1
    # --route_targets needs --route
    assert main(base + ["--route_targets", "127.0.0.1:1"]) == 1
    # --route excludes the model-serving modes
    assert main(base + ["--route", "--route_targets", "127.0.0.1:1",
                        "--serve_lm"]) == 1
    # mismatched signals list
    assert main(base + ["--route",
                        "--route_targets", "127.0.0.1:1,127.0.0.1:2",
                        "--route_signals", "http://127.0.0.1:3"]) == 1


def test_router_fleet_rollup_shows_roles_and_wanted(fleet):
    """FleetCollector treats the router as a first-class target: role
    columns, the wanted_replicas gauge, ?format=prom re-export."""
    import urllib.request

    from dnn_tpu import obs
    from dnn_tpu.obs.fleet import FleetCollector
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    router = fleet["router"]
    # router obs endpoint: its statusz (role=router) + the shared
    # registry (which carries the router gauges)
    srv = obs.serve_metrics(0, status=router.statusz)
    try:
        col = FleetCollector({"router": f"http://127.0.0.1:{srv.port}"},
                             interval_s=30.0)
        col.poll_once()
        z = col.fleetz()
        row = z["stages"]["router"]
        assert row["role"] == "router"
        assert row.get("wanted_replicas") is not None
        assert z["fleet"]["wanted_replicas"] is not None
        prom = col.render_prom()
        assert "dnn_tpu_fleet_stage_role" in prom
        assert "dnn_tpu_wanted_replicas" in prom
        # raw endpoint carries the router series for any plain scraper
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/metrics", timeout=5).read()
        assert b"dnn_tpu_router_queue_depth" in raw
        col.close()
    finally:
        srv.close()


def test_router_drain_hands_queued_work_to_sibling(fleet, client):
    """LAST test in the module (it drains r0 for good): draining one
    replica mid-traffic loses nothing — its rejections are retried on
    the sibling by the ROUTER, invisibly to the client."""
    from dnn_tpu import obs

    s1, s2 = fleet["servers"]
    rid_before = s2.batcher._next_rid
    errors = []

    def pound(i):
        try:
            client.generate(_prompt(), max_new_tokens=4, seed=100 + i,
                            timeout=30.0)
        except Exception as e:  # noqa: BLE001 — asserted below
            errors.append(e)

    threads = [threading.Thread(target=pound, args=(i,))
               for i in range(6)]
    for t in threads[:2]:
        t.start()
    s1._drainz()  # drain r0 while traffic is in flight
    for t in threads[2:]:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    # everything that r0 turned away landed on r1
    assert s2.batcher._next_rid > rid_before
    # the replica set noticed the drain (healthz 503s) — r0 leaves the
    # serving set within a few monitor ticks
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if fleet["rset"].replicas["r0"].state != "serving":
            break
        time.sleep(0.3)
    assert fleet["rset"].replicas["r0"].state in ("draining", "dead")
    # ...and the router recorded sibling retries for the handed-back work
    assert obs.flight.recorder().events(kind="router_retry_sibling") \
        or s2.batcher._next_rid - rid_before >= 4
