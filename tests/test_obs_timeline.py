"""Step-timeline attribution (ISSUE 11): StepClock phase accounting,
capture analysis, /stepz, and the sidecar-meta alignment.

Covers the layer's contracts:
  * phase sums cover the externally measured wall (no dark time);
  * derived-series arithmetic (dispatch slack, sync tax, host
    fraction) under a deterministic injected clock;
  * admit attribution from real submit() calls;
  * the one-None-check gate (DNN_TPU_OBS off -> begin() is None and a
    stepped pool records nothing);
  * analyze() goldens over synthetic Perfetto JSON, including
    truncated/garbage inputs failing loud;
  * step<->capture alignment via the profile.py sidecar meta;
  * /stepz scrape (JSON + ?format=prom + ?format=trace);
  * CLI smoke (`python -m dnn_tpu.obs timeline --selftest`).
"""

import gzip
import json
import os
import time
import urllib.request

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.obs import timeline as tl
from dnn_tpu.obs.timeline import PHASES, StepClock, analyze
from dnn_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _obs_on():
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _fake_clock(**kw):
    """StepClock on an injected, manually advanced clock."""
    t = [100.0]
    clk = StepClock(registry=kw.pop("registry", Metrics()),
                    now=lambda: t[0], **kw)
    return clk, t


def _drive(clk, t, *, admit=0.0, host=0.001, dispatch=0.002, wait=0.004,
           commit=0.001, obs_p=0.001, n_adv=4):
    if admit:
        t[0] += admit
        clk.note_admit(t[0] - admit)
    rec = clk.begin()
    assert rec is not None
    for phase, dt in (("host", host), ("dispatch", dispatch),
                      ("wait", wait), ("commit", commit),
                      ("obs", obs_p)):
        t[0] += dt
        clk.mark(rec, phase)
    clk.end(rec, n_adv=n_adv)
    return rec


# ----------------------------------------------------------------------
# StepClock arithmetic (deterministic injected clock)
# ----------------------------------------------------------------------

def test_derived_series_arithmetic():
    clk, t = _fake_clock()
    for _ in range(4):
        _drive(clk, t, admit=0.0005)
        t[0] += 0.001  # inter-step gap, deliberately dark
    s = clk.summary()
    assert s["window_steps"] == 4 and s["steps_total"] == 4
    # per step: wall 9.5 ms (9 in-step + 0.5 admit), host 3.5, device 6
    assert s["host_fraction"] == pytest.approx(3.5 / 9.5, abs=1e-3)
    assert s["dispatch_slack"] == pytest.approx(3.5 / 6.0, abs=1e-3)
    assert s["sync_tax"] == pytest.approx(4.0 / 9.5, abs=1e-3)
    assert s["phases"]["wait"]["mean_ms"] == pytest.approx(4.0, abs=1e-6)
    assert s["tokens"] == 16


def test_dispatch_slack_tracks_injected_device_time():
    """A slower fake device (longer wait) must LOWER the slack — host
    work unchanged, more device time to hide it under."""
    fast, tf = _fake_clock()
    slow, ts = _fake_clock()
    _drive(fast, tf, wait=0.002)
    _drive(slow, ts, wait=0.020)
    assert slow.dispatch_slack() < fast.dispatch_slack()
    assert slow.sync_tax() > fast.sync_tax()
    # exact: host 3 ms over device (2 + dispatch 2) vs (20 + 2)
    assert fast.dispatch_slack() == pytest.approx(0.003 / 0.004, 1e-6)
    assert slow.dispatch_slack() == pytest.approx(0.003 / 0.022, 1e-6)


def test_ring_bounded_and_records():
    clk, t = _fake_clock(capacity=4)
    for _ in range(9):
        _drive(clk, t)
    assert clk.steps_total == 9
    recs = clk.records()
    assert len(recs) == 4  # bounded
    assert all(set(r["phases"]) == set(PHASES) - {"admit"} for r in recs)
    assert clk.records(last=2)[-1]["t0"] == recs[-1]["t0"]


def test_registry_histograms_and_gauges_land():
    reg = Metrics()
    clk, t = _fake_clock(registry=reg)
    for _ in range(3):
        _drive(clk, t)
    clk.flush()  # batched flush: tests force it (FLUSH_EVERY is 32)
    snap = reg.snapshot()
    assert snap["counters"]["step.steps_total"] == 3
    h = snap["histogram"]['step.phase_seconds{phase="wait"}']
    assert h["count"] == 3
    assert snap["histogram"]["step.wall_seconds"]["count"] == 3
    assert snap["gauges"]["step.host_fraction"] == pytest.approx(
        3.0 / 9.0, abs=1e-3)  # no admits in this test
    # render carries the step family for scrapers
    from dnn_tpu.utils.metrics import render_prometheus

    text = render_prometheus(reg)
    assert "step_phase_seconds_bucket" in text
    assert "step.host_fraction".replace(".", "_") in text


def test_summary_flushes_pending():
    """A scrape must never read a stale histogram: summary() flushes
    the batch even below FLUSH_EVERY."""
    reg = Metrics()
    clk, t = _fake_clock(registry=reg)
    _drive(clk, t)
    assert reg.snapshot()["counters"].get("step.steps_total") is None
    clk.summary()
    assert reg.snapshot()["counters"]["step.steps_total"] == 1


def test_chrome_trace_phase_slices():
    clk, t = _fake_clock()
    _drive(clk, t, admit=0.0005)
    _drive(clk, t)
    ct = clk.chrome_trace()
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 11  # 5 phases x 2 steps + 1 admit slice
    names = [e["name"] for e in xs if e["args"].get("step") == 0]
    assert names[0] == "admit"
    # in-step slices are contiguous: each starts where the last ended
    step0 = [e for e in xs if e["args"].get("step") == 0
             and e["name"] != "admit"]
    for a, b in zip(step0, step0[1:]):
        assert b["ts"] == pytest.approx(a["ts"] + a["dur"], abs=1e-3)
    assert {e["name"] for e in ct["traceEvents"]
            if e.get("ph") == "M"} == {"process_name", "thread_name"}


def test_metrics_bulk_hists():
    m = Metrics()
    m.bulk(hists={"x_seconds": [0.1, 0.2]}, hist_buckets=(0.15, 1.0))
    snap = m.snapshot()["histogram"]["x_seconds"]
    assert snap["count"] == 2
    assert snap["buckets"][0.15] == 1  # 0.1 below, 0.2 above


# ----------------------------------------------------------------------
# the instrumented pool (real batcher)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def pool():
    import jax

    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2,
                        n_head=2, n_embd=64)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return ContinuousBatcher(cfg, prepared, slots=2, max_len=32,
                             prompt_pad=8)


def _round(srv, new_tokens=12):
    for i in range(srv.slots):
        srv.submit(np.arange(1, 5), new_tokens, seed=i)
    srv.drain()
    srv.results.clear()
    srv.finish_reasons.clear()


def test_phase_sum_covers_measured_wall(pool):
    """The probe's coverage assertion in miniature: attributed seconds
    vs an EXTERNAL wall clock around the round. The bound here is
    loose (0.85) because this pool's sub-ms steps make the python loop
    glue proportionally larger than the probe's asserted standard
    config — the 0.95 floor is asserted by step_timeline_probe."""
    clock = StepClock(capacity=1024)
    pool.step_clock = clock
    try:
        _round(pool)  # warm/compile outside the measured window
        base = clock.steps_total
        t0 = time.perf_counter()
        _round(pool)
        wall = time.perf_counter() - t0
        n = clock.steps_total - base
        assert n >= 10
        recs = clock.records()[-n:]
        attributed = sum(r["wall"] for r in recs)
        assert attributed <= wall * 1.001  # can't attribute time that
        # didn't pass
        assert attributed / wall >= 0.85, (attributed, wall)
        # every in-step phase present on every record
        for r in recs:
            assert set(r["phases"]) >= {"host", "dispatch", "wait",
                                        "commit", "obs"}, r
    finally:
        pool.step_clock = None


def test_admit_attributed_to_next_step(pool):
    clock = StepClock(capacity=64)
    pool.step_clock = clock
    try:
        pool.submit(np.arange(1, 5), 4, seed=0)
        pool.step()
        recs = clock.records()
        assert recs, "step must record"
        first = recs[-1]
        assert first["phases"].get("admit", 0.0) > 0.0
        assert first["admit_slices"], first
        # the admit slice predates the step's own t0
        a0, a1 = first["admit_slices"][0]
        assert a0 < a1 <= first["t0"] + 1e-3
        pool.drain()
        pool.results.clear()
        pool.finish_reasons.clear()
    finally:
        pool.step_clock = None


def test_gate_off_records_nothing(pool):
    clock = StepClock(capacity=64)
    pool.step_clock = clock
    try:
        obs.set_enabled(False)
        assert clock.begin() is None  # the one-None-check gate
        pool.submit(np.arange(1, 5), 4, seed=0)
        pool.drain()
        pool.results.clear()
        pool.finish_reasons.clear()
        assert clock.steps_total == 0
        assert clock.records() == []
        obs.set_enabled(True)  # re-enable takes effect immediately
        _round(pool, new_tokens=4)
        assert clock.steps_total > 0
    finally:
        pool.step_clock = None


def test_statusz_step_component(pool):
    clock = StepClock(capacity=64)
    pool.step_clock = clock
    try:
        _round(pool, new_tokens=4)
        comp = clock.status_component()
        assert comp["state"] == "ok"
        assert comp["steps_total"] == clock.steps_total
        assert comp["last_wall_ms"] > 0
        assert comp["last_step_age_s"] >= 0
        assert "host fraction" in comp["detail"]
    finally:
        pool.step_clock = None


# ----------------------------------------------------------------------
# analyze(): synthetic capture goldens
# ----------------------------------------------------------------------

def _synthetic_trace(tmp_path, *, gz=True, meta=None, n_steps=3,
                     step_ms=10.0, busy_ms=6.0, lead_ms=1.5):
    """One 6 ms device op per 10 ms step, plus track metadata — the
    deterministic shape the selftest also pins."""
    events = [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": 7, "tid": 2, "name": "thread_name",
         "args": {"name": "tf_XLATfrtCpuClient"}},
        {"ph": "M", "pid": 7, "tid": 1, "name": "thread_name",
         "args": {"name": "python"}},
    ]
    for i in range(n_steps):
        events.append({"ph": "X", "pid": 7, "tid": 2, "name": "fusion.1",
                       "ts": (lead_ms + step_ms * i) * 1e3,
                       "dur": busy_ms * 1e3,
                       "args": {"hlo_op": "fusion.1"}})
    # a host-python event must NOT count as device time
    events.append({"ph": "X", "pid": 7, "tid": 1, "name": "step()",
                   "ts": 0.0, "dur": n_steps * step_ms * 1e3})
    doc = {"traceEvents": events, "displayTimeUnit": "ns"}
    name = "vm.trace.json.gz" if gz else "vm.trace.json"
    p = os.path.join(tmp_path, name)
    if gz:
        with gzip.open(p, "wt") as f:
            json.dump(doc, f)
    else:
        with open(p, "w") as f:
            json.dump(doc, f)
    if meta is not None:
        with open(os.path.join(tmp_path, "meta.json"), "w") as f:
            json.dump(meta, f)
    return p


def test_analyze_synthetic_golden(tmp_path):
    d = str(tmp_path)
    _synthetic_trace(d)
    a = analyze(d)  # dir form resolves the trace file itself
    assert a["device"]["ops"] == 3
    assert a["device"]["busy_s"] == pytest.approx(0.018, abs=1e-9)
    # window = event span (no meta): 1.5 .. 27.5 ms -> 26 ms? no: the
    # host event spans 0..30 ms, so the window is 30 ms
    assert a["window_s"] == pytest.approx(0.030, abs=1e-6)
    assert a["device"]["busy_frac"] == pytest.approx(0.6, abs=1e-3)
    assert a["host_gaps"]["count"] == 2
    assert a["host_gaps"]["p50_ms"] == pytest.approx(4.0, abs=1e-3)
    assert a["top_ops"][0]["name"] == "fusion.1"
    assert a["top_ops"][0]["frac_of_device"] == pytest.approx(1.0)
    # host python track exists and is distinct from the device ops
    assert any("python" in k for k in a["tracks"])
    assert a["steps"] is None  # no meta -> no step section


def test_analyze_plain_json_equals_gzip(tmp_path):
    d1, d2 = tmp_path / "a", tmp_path / "b"
    d1.mkdir()
    d2.mkdir()
    pg = _synthetic_trace(str(d1), gz=True)
    pj = _synthetic_trace(str(d2), gz=False)
    ag, aj = analyze(pg), analyze(pj)
    for k in ("window_s", "events"):
        assert ag[k] == aj[k]
    assert ag["device"] == aj["device"]


def test_analyze_rejects_garbage_and_truncated(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text("definitely { not json")
    with pytest.raises(ValueError):
        analyze(str(bad))
    # truncated gzip: a valid header with a cut-off body
    good = _synthetic_trace(str(tmp_path))
    data = open(good, "rb").read()
    trunc = tmp_path / "trunc.trace.json.gz"
    trunc.write_bytes(data[: len(data) // 2])
    with pytest.raises(ValueError):
        analyze(str(trunc))
    # valid JSON, wrong shape
    shape = tmp_path / "shape.json"
    shape.write_text(json.dumps({"notTraceEvents": []}))
    with pytest.raises(ValueError):
        analyze(str(shape))
    # empty dir
    empty = tmp_path / "emptydir"
    empty.mkdir()
    with pytest.raises(ValueError):
        analyze(str(empty))


def test_step_capture_alignment_via_meta(tmp_path):
    """Synthetic meta + synthetic clock records: each 10 ms step holds
    one 6 ms device op -> per-step overlap 6/10, steps_in_capture from
    the counter range."""
    d = str(tmp_path)
    _synthetic_trace(d, meta={"perf_begin": 100.0, "perf_end": 100.032,
                              "step_begin": 5, "step_end": 8,
                              "backend": "cpu"})
    clk, t = _fake_clock()
    t[0] = 100.0015  # first step entry aligns with the first device op
    for _ in range(3):
        _drive(clk, t, host=0.0, dispatch=0.002, wait=0.004,
               commit=0.002, obs_p=0.002)  # wall 10 ms
        # no gap: steps are back to back like the synthetic ops
    a = analyze(d, clock=clk)
    st = a["steps"]
    assert st["aligned"] and st["n_steps"] == 3
    assert st["steps_in_capture"] == 3
    assert st["backend"] == "cpu"
    assert st["mean_wall_ms"] == pytest.approx(10.0, abs=1e-3)
    assert st["mean_device_busy_ms"] == pytest.approx(6.0, abs=1e-2)
    assert st["device_overlap_frac"] == pytest.approx(0.6, abs=1e-3)
    # with meta, the window is the ARMED window, not the event span
    assert a["window_s"] == pytest.approx(0.032, abs=1e-6)


def test_real_capture_sidecar_meta_and_alignment(pool, tmp_path):
    """End to end on a REAL jax.profiler capture: profile.py writes the
    sidecar meta (perf bounds, step range, backend), and analyze()
    places the pool's steps inside it."""
    from dnn_tpu.obs.profile import capture_step

    clock = StepClock(capacity=1024).install()
    pool.step_clock = clock
    try:
        _round(pool, new_tokens=6)  # warm
        before = clock.steps_total
        path, _ = capture_step(lambda: _round(pool, new_tokens=6),
                               capture_root=str(tmp_path))
        meta = json.load(open(os.path.join(path, "meta.json")))
        assert meta["step_begin"] == before
        assert meta["step_end"] == clock.steps_total
        assert meta["perf_end"] > meta["perf_begin"]
        assert meta["backend"] == "cpu"
        a = analyze(path, clock=clock)
        st = a["steps"]
        assert st["aligned"], st
        assert st["n_steps"] == clock.steps_total - before
        assert 0.0 < st["device_overlap_frac"] <= 1.0
        assert a["device"]["ops"] > 0
    finally:
        pool.step_clock = None


# ----------------------------------------------------------------------
# /stepz + CLI
# ----------------------------------------------------------------------

def test_stepz_endpoint_json_prom_trace():
    clk, t = _fake_clock()
    for _ in range(3):
        _drive(clk, t, admit=0.0005)
    srv = obs.serve_metrics(0, stepclock=clk)
    try:
        base = f"http://127.0.0.1:{srv.port}/stepz"
        s = json.loads(urllib.request.urlopen(base, timeout=10).read())
        assert s["window_steps"] == 3
        assert s["phases"]["wait"]["mean_ms"] == pytest.approx(4.0)
        prom = urllib.request.urlopen(base + "?format=prom",
                                      timeout=10).read().decode()
        assert "dnn_tpu_step_host_fraction" in prom
        assert 'dnn_tpu_step_phase_frac{phase="wait"}' in prom
        ct = json.loads(urllib.request.urlopen(
            base + "?format=trace&last=2", timeout=10).read())
        xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
        assert len(xs) == 12  # 2 steps x (5 phases + admit)
        code = urllib.request.urlopen(
            base + "?format=nope", timeout=10)
    except urllib.error.HTTPError as e:
        assert e.code == 400
    finally:
        srv.close()


def test_stepz_404_without_clock():
    srv = obs.serve_metrics(0)
    try:
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/stepz",
                               timeout=10)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404
    finally:
        srv.close()


def test_cli_selftest_and_path(tmp_path, capsys):
    from dnn_tpu.obs.__main__ import main

    assert main(["timeline", "--selftest"]) == 0
    out = capsys.readouterr().out
    assert "timeline selftest ok" in out
    p = _synthetic_trace(str(tmp_path))
    assert main(["timeline", p]) == 0
    out = capsys.readouterr().out
    assert "device: busy" in out and "fusion.1" in out
    assert main(["timeline", p, "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["device"]["ops"] == 3
