"""TP x PP composition tests: tensor-sharded blocks inside the stacked
pipeline over a {stage, model} mesh, and the full Megatron 3D
{data, stage, model} recipe.

The reference's only strategy is pipeline parallelism (SURVEY §2:
node.py:70-94); these tests pin the composed forms against it:
  * forward parity: TP x PP pipeline output == full single-device model;
  * training parity: loss AND gradients == the 1D stage-only pipeline
    (fp-reassociation tolerance) at {stage: 2, model: 2};
  * the 3D {data: 2, stage: 2, model: 2} leg over all 8 virtual devices.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS, make_mesh
from dnn_tpu.parallel.pipeline import spmd_pipeline_stacked
from dnn_tpu.train import (
    cross_entropy,
    gpt_tp_pp_specs,
    make_pipeline_train_step,
)

CFG = gpt.PRESETS["gpt2-test"]  # L=4, H=4, C=64, vocab=256


def _stage_stacked(params, num_stages):
    stacked = gpt.stack_blocks(params, range(CFG.n_layer))
    per = CFG.n_layer // num_stages
    return jax.tree.map(
        lambda p: p.reshape(num_stages, per, *p.shape[1:]), stacked)


def _aux(params):
    return {k: v for k, v in params.items() if not k.startswith("h_")}


def _tp_stage_stacked(params, num_stages, tp):
    """Stage-stacked blocks with the qkv columns reordered shard-major."""
    return gpt.prepare_tp_blocks(
        _stage_stacked(params, num_stages), CFG, tp)


def test_tp_block_fn_matches_plain_blocks_single_axis():
    """A pure-TP sanity check first: the TP block over a {model: 2} mesh
    equals the plain stacked blocks on one device."""
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    stacked = gpt.stack_blocks(params, range(CFG.n_layer))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, CFG.n_embd))

    want = gpt.blocks_scan(stacked, x, cfg=CFG)

    tp = 2
    mesh = make_mesh({MODEL_AXIS: tp}, jax.devices()[:tp])
    tp_stacked = gpt.prepare_tp_blocks(stacked, CFG, tp)
    block_fn = gpt.make_tp_block_fn(CFG)

    from jax.sharding import PartitionSpec as P

    # specs without the leading stage axis: drop it from the TP x PP table
    def strip_stage(spec):
        return P(*spec[1:])

    specs = jax.tree.map(
        strip_stage,
        gpt_tp_pp_specs(jax.tree.map(lambda p: p[None], tp_stacked)),
        is_leaf=lambda s: isinstance(s, P))

    got = jax.jit(lambda p, xx: jax.shard_map(
        block_fn, mesh=mesh,
        in_specs=(specs, P()), out_specs=P(), check_vma=False,
    )(p, xx))(tp_stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_tp_pp_forward_matches_full_model():
    """{stage: 2, model: 2} pipeline forward == full model logits."""
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    aux = _aux(params)
    ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                             CFG.vocab_size, dtype=jnp.int32)

    full = gpt.make_apply(CFG)(params, ids)

    mesh = make_mesh({STAGE_AXIS: 2, MODEL_AXIS: 2}, jax.devices()[:4])
    tp_stacked = _tp_stage_stacked(params, 2, 2)
    specs = gpt_tp_pp_specs(tp_stacked)
    block_fn = gpt.make_tp_block_fn(CFG)

    def pipe(ids_in):
        x = gpt.embed(aux, ids_in, cfg=CFG)
        h = spmd_pipeline_stacked(
            block_fn, tp_stacked, x, mesh=mesh, num_microbatches=2,
            param_specs=specs)
        return gpt.head(aux, h.astype(jnp.float32), cfg=CFG)

    got = pipe(ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(full),
                               atol=2e-4, rtol=2e-4)


def _loss_and_grads_1d(params, tokens, num_stages=2, mbs=2):
    """Reference: the existing 1D stage-only pipeline loss and grads."""
    aux = _aux(params)
    stacked = _stage_stacked(params, num_stages)
    mesh = make_mesh({STAGE_AXIS: num_stages}, jax.devices()[:num_stages])

    def loss_fn(stacked, aux):
        x = gpt.embed(aux, tokens[:, :-1], cfg=CFG)
        h = spmd_pipeline_stacked(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=CFG),
            stacked, x, mesh=mesh, num_microbatches=mbs)
        logits = gpt.head(aux, h.astype(jnp.float32), cfg=CFG)
        return cross_entropy(logits, tokens[:, 1:])

    (lval, (g_st, g_aux)) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        stacked, aux)
    return lval, g_st, g_aux


def test_tp_pp_loss_and_grads_match_1d_pipeline():
    """{stage: 2, model: 2} training: loss and ALL gradients equal the 1D
    pipeline's (the composition must not change the math)."""
    params = gpt.init(jax.random.PRNGKey(2), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (4, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    want_loss, want_g_st, want_g_aux = _loss_and_grads_1d(params, tokens)

    aux = _aux(params)
    mesh = make_mesh({STAGE_AXIS: 2, MODEL_AXIS: 2}, jax.devices()[:4])
    tp_stacked = _tp_stage_stacked(params, 2, 2)
    specs = gpt_tp_pp_specs(tp_stacked)
    block_fn = gpt.make_tp_block_fn(CFG)

    def loss_fn(stacked, aux):
        x = gpt.embed(aux, tokens[:, :-1], cfg=CFG)
        h = spmd_pipeline_stacked(
            block_fn, stacked, x, mesh=mesh, num_microbatches=2,
            param_specs=specs)
        logits = gpt.head(aux, h.astype(jnp.float32), cfg=CFG)
        return cross_entropy(logits, tokens[:, 1:])

    lval, (g_st, g_aux) = jax.value_and_grad(loss_fn, argnums=(0, 1))(
        tp_stacked, aux)

    np.testing.assert_allclose(float(lval), float(want_loss), atol=1e-5,
                               rtol=1e-5)
    # aux grads (embed/head) compare directly
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4),
        g_aux, want_g_aux)
    # block grads: undo the qkv shard-major reorder before comparing.
    # reorder is column-permutation by shard; invert by re-slicing: the TP
    # layout is [Q_0 K_0 V_0 | Q_1 K_1 V_1]; the plain layout [Q | K | V].
    c = CFG.n_embd
    shard = c // 2

    def unreorder(a):  # (..., 3C) shard-major -> [Q | K | V]
        pieces = {"q": [], "k": [], "v": []}
        for t in range(2):
            base = t * 3 * shard
            pieces["q"].append(a[..., base: base + shard])
            pieces["k"].append(a[..., base + shard: base + 2 * shard])
            pieces["v"].append(a[..., base + 2 * shard: base + 3 * shard])
        return jnp.concatenate(
            pieces["q"] + pieces["k"] + pieces["v"], axis=-1)

    g_qkv_plain = {
        "kernel": unreorder(g_st["attn"]["qkv"]["kernel"]),
        "bias": unreorder(g_st["attn"]["qkv"]["bias"]),
    }
    np.testing.assert_allclose(
        np.asarray(g_qkv_plain["kernel"]),
        np.asarray(want_g_st["attn"]["qkv"]["kernel"]),
        atol=3e-4, rtol=3e-4)
    np.testing.assert_allclose(
        np.asarray(g_qkv_plain["bias"]),
        np.asarray(want_g_st["attn"]["qkv"]["bias"]),
        atol=3e-4, rtol=3e-4)
    for path in (("ln_1",), ("ln_2",), ("attn", "proj"), ("mlp", "fc"),
                 ("mlp", "proj")):
        got_sub, want_sub = g_st, want_g_st
        for k in path:
            got_sub, want_sub = got_sub[k], want_sub[k]
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4),
            got_sub, want_sub)


def test_3d_data_stage_model_train_step():
    """The full Megatron 3D recipe on all 8 virtual devices:
    {data: 2, stage: 2, model: 2}. Loss matches the 1D pipeline on the
    same global batch, and params actually move."""
    params = gpt.init(jax.random.PRNGKey(4), CFG)
    aux = _aux(params)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (8, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    want_loss, _, _ = _loss_and_grads_1d(params, tokens, num_stages=2,
                                         mbs=2)

    mesh = make_mesh({DATA_AXIS: 2, STAGE_AXIS: 2, MODEL_AXIS: 2},
                     jax.devices()[:8])
    tp_stacked = _tp_stage_stacked(params, 2, 2)
    specs = gpt_tp_pp_specs(tp_stacked)
    block_fn = gpt.make_tp_block_fn(CFG)
    opt = optax.sgd(1e-2)

    step = make_pipeline_train_step(
        block_fn,
        lambda ax, ids: gpt.embed(ax, ids, cfg=CFG),
        lambda ax, h: gpt.head(ax, h.astype(jnp.float32), cfg=CFG),
        opt, mesh, num_microbatches=2, data_axis=DATA_AXIS,
        param_specs=specs)

    opt_states = (opt.init(tp_stacked), opt.init(aux))
    new_st, new_aux, opt_states, lval = step(
        tp_stacked, aux, opt_states, tokens)
    np.testing.assert_allclose(float(lval), float(want_loss), atol=1e-4,
                               rtol=1e-4)
    # params moved
    moved = jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), new_st, tp_stacked)
    assert max(jax.tree.leaves(moved)) > 0

    # a second step still runs (shardings stable across calls)
    _, _, _, lval2 = step(new_st, new_aux, opt_states, tokens)
    assert float(lval2) < float(lval)


def test_tp_pp_rejects_indivisible_heads():
    with pytest.raises(ValueError, match="divisible"):
        gpt.prepare_tp_blocks(
            gpt.stack_blocks(gpt.init(jax.random.PRNGKey(0), CFG),
                             range(CFG.n_layer)), CFG, 3)
