"""GPT-MoE family: forward shape, dense==EP parity, pipeline partition
parity, registry wiring, and a training smoke test.

The family has no reference counterpart (SURVEY.md §2: no MoE) — these
tests pin the invariants that make EP a placement choice: the dense
grouped forward equals the shard_map all_to_all forward exactly, and the
staged pipeline equals the full model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import get_model
from dnn_tpu.models import gpt_moe
from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh


@pytest.fixture(scope="module")
def moe_setup():
    spec = get_model("gpt2-moe-test")
    params = spec.init(jax.random.PRNGKey(0))
    ids = spec.example_input(batch_size=4, seq_len=16, rng=jax.random.PRNGKey(1))
    return spec, params, ids


def test_forward_shape(moe_setup):
    spec, params, ids = moe_setup
    logits = spec.apply(params, ids)
    assert logits.shape == (4, 16, spec.config.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.parametrize("n_dev", [2, 4])
def test_ep_matches_dense(moe_setup, n_dev):
    """Full-model EP forward == dense forward with groups=n (exact routing
    parity; fp tolerance only for reassociated matmuls)."""
    spec, params, ids = moe_setup
    cfg = spec.config
    mesh = make_mesh({EXPERT_AXIS: n_dev}, jax.devices()[:n_dev])
    dense = np.asarray(gpt_moe.make_apply(cfg, groups=n_dev)(params, ids))
    ep = np.asarray(jax.jit(gpt_moe.make_apply_ep(cfg, mesh))(params, ids))
    np.testing.assert_allclose(ep, dense, rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("num_parts", [1, 2])
def test_partition_parity(moe_setup, num_parts):
    spec, params, ids = moe_setup
    h = ids
    for stage in spec.partition(num_parts):
        h = stage.apply(stage.slice_params(params), h)
    np.testing.assert_allclose(
        np.asarray(h), np.asarray(spec.apply(params, ids)), rtol=1e-5, atol=1e-5
    )


def test_param_keys_cover_model(moe_setup):
    spec, params, _ = moe_setup
    for n in (1, 2):
        keys = [k for s in spec.partition(n) for k in s.param_keys]
        assert sorted(keys) == sorted(params)


def test_ep_train_step_smoke(moe_setup):
    """grad of an EP-forward LM loss flows into expert + router weights."""
    spec, params, ids = moe_setup
    cfg = spec.config
    mesh = make_mesh({EXPERT_AXIS: 2}, jax.devices()[:2])
    ep_apply = gpt_moe.make_apply_ep(cfg, mesh)

    def loss_fn(p):
        logits = ep_apply(p, ids)
        tgt = jnp.roll(ids, -1, axis=1)
        lp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(lp, tgt[..., None], axis=-1).mean()

    loss, g = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    assert float(jnp.abs(g["h_0"]["moe"]["wi"]).sum()) > 0
    assert float(jnp.abs(g["h_0"]["moe"]["router"]["kernel"]).sum()) > 0


def test_registry_presets():
    spec = get_model("gpt2-moe-test")
    assert spec.config.n_experts == 4
    assert "make_apply_ep" in spec.extras


def test_engine_serves_moe_by_config():
    """The engine must NOT route GPTMoEConfig into the dense-GPT stacked
    runtime (whose blocks read params['mlp']); the generic partitioned
    path serves it."""
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict({
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(2)],
        "num_parts": 2,
        "model": "gpt2-moe-test",
        "device_type": "cpu",
        "runtime": "spmd",
        # microbatching changes MoE routing groups (each microbatch routes
        # independently — see gpt_moe.make_partition); parity vs the dense
        # forward needs the whole batch as one group
        "microbatches": 1,
    })
    eng = PipelineEngine(cfg, rng_seed=0)
    ids = np.asarray(eng.spec.example_input(batch_size=2, seq_len=8))
    np.testing.assert_allclose(
        np.asarray(eng.run(ids)),
        np.asarray(eng.spec.apply(eng.params, ids)),
        rtol=1e-4, atol=1e-4,
    )


def test_ep_accepts_prepared_params(moe_setup):
    """Pre-stacked params ({"blocks": ...}) skip the per-call restack."""
    spec, params, ids = moe_setup
    cfg = spec.config
    from dnn_tpu.models.gpt import prepare_stacked

    mesh = make_mesh({EXPERT_AXIS: 2}, jax.devices()[:2])
    ep = gpt_moe.make_apply_ep(cfg, mesh)
    raw = np.asarray(ep(params, ids))
    prepped = np.asarray(ep(prepare_stacked(params, cfg), ids))
    np.testing.assert_array_equal(raw, prepped)


def test_ep_int8_expert_stacks():
    """EP over a quantize_tree'd GPT-MoE tree: the pytree-derived spec
    shards the wi/wo stacks AND their scale leaves — parity with the
    grouped dense forward on the quantized params."""
    from dnn_tpu import quant
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    cfg = gpt_moe.PRESETS["gpt2-moe-test"]
    n = min(4, cfg.n_experts)
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    params = gpt_moe.init(jax.random.PRNGKey(30), cfg)
    q = quant.quantize_tree(params)
    assert q["h_0"]["moe"]["wi"].dtype == jnp.int8
    ids = np.random.RandomState(31).randint(0, cfg.vocab_size, (n, 8))
    want = np.asarray(gpt_moe.make_apply(cfg, groups=n)(
        q, jnp.asarray(ids)))
    got = np.asarray(gpt_moe.make_apply_ep(cfg, mesh)(q, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)
