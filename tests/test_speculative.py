"""Speculative decoding tests.

Invariants (Leviathan et al. 2023; the reference has no decode loop at all,
/root/reference/node.py:137-200, so the oracle is our own `make_generate`):

  * greedy speculative output is token-for-token IDENTICAL to target-only
    greedy decode — acceptance changes speed, never content;
  * with draft == target every proposal is accepted (ratio == 1);
  * sampled output follows the target distribution exactly — checked
    statistically: the empirical first-token histogram over many seeded
    runs must match the target's exact softmax row.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.speculative import make_speculative_generate

T_CFG = gpt.PRESETS["gpt2-test"]  # block_size=64, vocab=256, L=4, H=4, C=64
D_CFG = gpt.GPTConfig(block_size=64, vocab_size=256, n_layer=1, n_head=2, n_embd=32)

# tiny-vocab pair for statistical tests (histograms converge)
ST_T = gpt.GPTConfig(block_size=64, vocab_size=32, n_layer=2, n_head=2, n_embd=32)
ST_D = gpt.GPTConfig(block_size=64, vocab_size=32, n_layer=1, n_head=2, n_embd=16)


def _pair(t_cfg=T_CFG, d_cfg=D_CFG, seed=0, sharpen=1.0):
    tp = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), t_cfg), t_cfg)
    dp = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed + 1), d_cfg), d_cfg)
    if sharpen != 1.0:
        # random-init models emit near-uniform distributions (TV(target,
        # draft) ~ 0.06 over 32 tokens) — too close for the statistical
        # tests to distinguish target- from draft-following. Scaling the
        # target's LM head sharpens its softmax so the two visibly differ.
        tp = dict(tp)
        tp["lm_head"] = {"kernel": tp["lm_head"]["kernel"] * sharpen}
    return tp, dp


def test_greedy_token_parity_vs_plain_generate():
    tp, dp = _pair()
    ids = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, T_CFG.vocab_size)
    n_new = 16
    spec = make_speculative_generate(
        T_CFG, D_CFG, max_new_tokens=n_new, k=4, temperature=0.0)
    plain = make_generate(T_CFG, max_new_tokens=n_new, temperature=0.0)
    got = np.asarray(spec(tp, dp, ids, jax.random.PRNGKey(0)))
    want = np.asarray(plain(tp, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_greedy_parity_across_prompt_lengths():
    # two different prompt lengths force two traces — guards against any
    # state smuggled across traces (the round-2 global-pos bug)
    tp, dp = _pair(seed=3)
    spec = make_speculative_generate(
        T_CFG, D_CFG, max_new_tokens=8, k=3, temperature=0.0)
    plain = make_generate(T_CFG, max_new_tokens=8, temperature=0.0)
    for p in (6, 11):
        ids = jax.random.randint(jax.random.PRNGKey(p), (1, p), 0, T_CFG.vocab_size)
        got = np.asarray(spec(tp, dp, ids, jax.random.PRNGKey(1)))
        want = np.asarray(plain(tp, ids, jax.random.PRNGKey(1)))
        np.testing.assert_array_equal(got, want)


def test_draft_equals_target_accepts_everything():
    tp, _ = _pair()
    ids = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0, T_CFG.vocab_size)
    for temp in (0.0, 1.0):
        spec = make_speculative_generate(
            T_CFG, T_CFG, max_new_tokens=12, k=4, temperature=temp,
            return_stats=True)
        _, stats = spec(tp, tp, ids, jax.random.PRNGKey(0))
        assert int(stats["accepted"]) == int(stats["proposed"]), (
            f"temp={temp}: draft==target must accept all proposals, got "
            f"{int(stats['accepted'])}/{int(stats['proposed'])}")


def test_acceptance_stats_sane():
    tp, dp = _pair(seed=7)
    ids = jax.random.randint(jax.random.PRNGKey(8), (1, 8), 0, T_CFG.vocab_size)
    spec = make_speculative_generate(
        T_CFG, D_CFG, max_new_tokens=16, k=4, temperature=1.0,
        return_stats=True)
    toks, stats = spec(tp, dp, ids, jax.random.PRNGKey(0))
    it, prop, acc = (int(stats[x]) for x in ("iterations", "proposed", "accepted"))
    assert prop == it * 4
    assert 0 <= acc <= prop
    # each iteration commits >= 1 token
    assert it <= 16
    t = np.asarray(toks)
    assert t.shape == (1, 16)
    assert (t >= 0).all() and (t < T_CFG.vocab_size).all()


def _first_token_hist(spec_fn, tp, dp, ids, n_draws, vocab):
    rngs = jax.random.split(jax.random.PRNGKey(42), n_draws)
    batched = jax.jit(jax.vmap(lambda r: spec_fn(tp, dp, ids, r)))
    toks = np.asarray(batched(rngs))[:, 0, 0]  # first generated token per draw
    return np.bincount(toks, minlength=vocab) / n_draws


@pytest.mark.parametrize("same_draft", [False, True])
def test_sampled_matches_target_distribution(same_draft):
    """Empirical first-token histogram vs the target's EXACT softmax row.

    same_draft=False exercises the rejection/residual-resample path;
    same_draft=True (draft == target) exercises pure-accept + bonus row.
    """
    tp, dp = _pair(ST_T, ST_D, seed=11, sharpen=6.0)
    d_cfg = ST_T if same_draft else ST_D
    d_prep = tp if same_draft else dp
    ids = jax.random.randint(jax.random.PRNGKey(12), (1, 8), 0, ST_T.vocab_size)

    spec = make_speculative_generate(
        ST_T, d_cfg, max_new_tokens=3, k=2, temperature=1.0)
    n = 2000
    hist = _first_token_hist(spec, tp, d_prep, ids, n, ST_T.vocab_size)

    logits = gpt.make_apply_stacked(ST_T)(tp, ids)
    exact = np.asarray(jax.nn.softmax(logits[0, -1].astype(jnp.float32)))

    tv = 0.5 * np.abs(hist - exact).sum()
    # E[TV] for n=2000 multinomial draws over 32 bins is ~0.05; 0.12 is a
    # comfortable 2.4x margin that still catches a wrong distribution
    # (e.g. sampling the draft unconditionally gives TV ~ 0.3+ here)
    assert tv < 0.12, f"TV(spec, target) = {tv:.3f}"


def test_sampled_distribution_differs_from_draft():
    """Negative control: the spec-decode marginal must track the TARGET,
    not the draft — otherwise the parity test above could pass vacuously
    on models that happen to agree."""
    tp, dp = _pair(ST_T, ST_D, seed=11, sharpen=6.0)
    ids = jax.random.randint(jax.random.PRNGKey(12), (1, 8), 0, ST_T.vocab_size)
    t_logits = gpt.make_apply_stacked(ST_T)(tp, ids)
    d_logits = gpt.make_apply_stacked(ST_D)(dp, ids)
    t_exact = np.asarray(jax.nn.softmax(t_logits[0, -1].astype(jnp.float32)))
    d_exact = np.asarray(jax.nn.softmax(d_logits[0, -1].astype(jnp.float32)))
    tv_models = 0.5 * np.abs(t_exact - d_exact).sum()
    assert tv_models > 0.2, (
        "fixture degenerate: target and draft agree; pick different seeds")

    spec = make_speculative_generate(
        ST_T, ST_D, max_new_tokens=3, k=2, temperature=1.0)
    hist = _first_token_hist(spec, tp, dp, ids, 2000, ST_T.vocab_size)
    tv_draft = 0.5 * np.abs(hist - d_exact).sum()
    assert tv_draft > 0.5 * tv_models, (
        f"spec histogram suspiciously close to the DRAFT dist (tv={tv_draft:.3f})")


def test_rejects_bad_shapes():
    tp, dp = _pair()
    spec = make_speculative_generate(T_CFG, D_CFG, max_new_tokens=4, k=4)
    with pytest.raises(ValueError):  # batch != 1
        spec(tp, dp, jnp.zeros((2, 8), jnp.int32), jax.random.PRNGKey(0))
    with pytest.raises(ValueError):  # prompt < k+2
        spec(tp, dp, jnp.zeros((1, 4), jnp.int32), jax.random.PRNGKey(0))
    with pytest.raises(ValueError):  # vocab mismatch
        make_speculative_generate(
            T_CFG, gpt.GPTConfig(vocab_size=128), max_new_tokens=4)
