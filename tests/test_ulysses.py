"""Ulysses (all-to-all) sequence parallelism: attention parity vs dense,
full-model parity vs the single-device forward and vs the ring method,
head-divisibility validation, and differentiability."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dnn_tpu.models import gpt
from dnn_tpu.ops.pallas.flash_attention import reference_attention
from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from dnn_tpu.parallel.ulysses import ulysses_attention_local


@pytest.mark.parametrize("n_dev", [2, 4])
def test_attention_parity(n_dev):
    b, h, t, d = 2, 4, 32, 8
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (b, h, t, d), jnp.float32)
        for i in range(3)
    )
    mesh = make_mesh({SEQ_AXIS: n_dev}, jax.devices()[:n_dev])
    got = jax.shard_map(
        lambda *args: ulysses_attention_local(*args, axis_name=SEQ_AXIS),
        mesh=mesh,
        in_specs=(P(None, None, SEQ_AXIS), P(None, None, SEQ_AXIS),
                  P(None, None, SEQ_AXIS)),
        out_specs=P(None, None, SEQ_AXIS),
        check_vma=False,
    )(q, k, v)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("n_dev", [2, 4])
def test_full_model_parity(n_dev):
    spec_cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), spec_cfg)
    prepared = gpt.prepare_stacked(params, spec_cfg)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 4 * n_dev), 0,
                             spec_cfg.vocab_size, dtype=jnp.int32)
    mesh = make_mesh({SEQ_AXIS: n_dev}, jax.devices()[:n_dev])
    dense = np.asarray(gpt.make_apply_stacked(spec_cfg)(prepared, ids))
    uly = np.asarray(
        gpt.make_apply_seq_parallel(spec_cfg, mesh, method="ulysses")(prepared, ids)
    )
    np.testing.assert_allclose(uly, dense, rtol=2e-4, atol=2e-4)
    ring = np.asarray(
        gpt.make_apply_seq_parallel(spec_cfg, mesh, method="ring")(prepared, ids)
    )
    np.testing.assert_allclose(uly, ring, rtol=2e-4, atol=2e-4)


def test_head_divisibility_validated():
    cfg = gpt.PRESETS["gpt2-test"]  # n_head = 4
    mesh = make_mesh({SEQ_AXIS: 8}, jax.devices()[:8])
    with pytest.raises(ValueError, match="divisible"):
        gpt.make_apply_seq_parallel(cfg, mesh, method="ulysses")
    with pytest.raises(ValueError, match="ring|ulysses"):
        gpt.make_apply_seq_parallel(cfg, mesh, method="nope")


def test_grad_flows():
    cfg = gpt.PRESETS["gpt2-test"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    mesh = make_mesh({SEQ_AXIS: 2}, jax.devices()[:2])
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    apply = gpt.make_apply_seq_parallel(cfg, mesh, method="ulysses")

    def loss(p):
        return jnp.mean(apply(p, ids).astype(jnp.float32) ** 2)

    g = jax.grad(loss)(prepared)
    total = sum(float(jnp.abs(l).sum()) for l in jax.tree.leaves(g))
    assert np.isfinite(total) and total > 0
