"""Sliding-window attention (Mistral-class) tests.

The reference has no attention at all (its GPT wrappers are stateless
full-sequence parts, /root/reference/partitions/gpt_model_parts.py), so
the window is pure widening — but it must compose with every runtime the
LLaMA family already rides. Strategy mirrors tests/test_models_llama.py:

  * HF parity: transformers.MistralForCausalLM == our forward on
    converted weights at T > window (the band itself is cross-checked
    against an independent implementation, not just our own mask);
  * masked-vs-rolling equivalence at the codec level (ring occupancy
    predicate == lower-bound mask over a full cache, wrap included);
  * rolling decode == dense-band full recompute, token for token, with
    the stream crossing the window boundary;
  * the continuous batcher (window-masked pool) == solo decode (rolling
    ring) — two different storage designs, one attention function.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama
from dnn_tpu.runtime.kvcache import FloatKV, RollingFloatKV

CFG = llama.PRESETS["mistral-test"]  # L=4, H=4, KV=2, C=64, V=256, W=16
DENSE = dataclasses.replace(CFG, sliding_window=None)


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_short_sequences_see_no_window():
    """T <= window: the band covers the whole causal triangle."""
    params = _params()
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, CFG.sliding_window),
                             0, CFG.vocab_size)
    a = llama.make_apply(CFG)(params, ids)
    b = llama.make_apply(DENSE)(params, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_long_sequences_are_banded():
    """T > window: late positions must IGNORE out-of-band tokens.
    Receptive field grows by one window per LAYER (the Mistral design's
    point), so the strict invariance check uses a single-layer config:
    perturbing a token more than W behind the last position leaves its
    logits bit-unchanged, while the dense model shifts."""
    cfg1 = dataclasses.replace(CFG, n_layer=1)
    dense1 = dataclasses.replace(cfg1, sliding_window=None)
    params = llama.init(jax.random.PRNGKey(1), cfg1)
    t = cfg1.sliding_window + 8
    ids = np.asarray(jax.random.randint(jax.random.PRNGKey(2), (1, t),
                                        0, cfg1.vocab_size))
    ids2 = ids.copy()
    ids2[0, 0] = (ids2[0, 0] + 1) % cfg1.vocab_size  # outside the last row's band
    w_a = np.asarray(llama.make_apply(cfg1)(params, jnp.asarray(ids)))
    w_b = np.asarray(llama.make_apply(cfg1)(params, jnp.asarray(ids2)))
    np.testing.assert_array_equal(w_a[0, -1], w_b[0, -1])
    d_a = np.asarray(llama.make_apply(dense1)(params, jnp.asarray(ids)))
    d_b = np.asarray(llama.make_apply(dense1)(params, jnp.asarray(ids2)))
    assert np.abs(d_a[0, -1] - d_b[0, -1]).max() > 0


def test_hf_mistral_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.MistralConfig)
    assert hf_cfg.sliding_window == CFG.sliding_window
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    t = CFG.sliding_window + 8  # past the window: the band is live
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, t))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))


@pytest.mark.parametrize("p_query", [5, 27])
def test_ring_codec_matches_masked_full_cache(p_query):
    """RollingFloatKV over a W-slot ring == FloatKV(window=W) over a
    full-length cache, fed the same position stream — before the first
    wrap (p=5 < W) and after it (p=27 > W)."""
    B, H, D, W, S = 2, 2, 8, 16, 40
    rng = np.random.RandomState(0)
    full = {"k": jnp.zeros((B, H, S, D)), "v": jnp.zeros((B, H, S, D))}
    ring = {"k": jnp.zeros((B, H, W, D)), "v": jnp.zeros((B, H, W, D))}
    flat, roll = FloatKV(window=W), RollingFloatKV(window=W)
    for p in range(p_query + 1):
        k = jnp.asarray(rng.randn(B, H, 1, D), jnp.float32)
        v = jnp.asarray(rng.randn(B, H, 1, D), jnp.float32)
        full = flat.write(full, k, v, p)
        ring = roll.write(ring, k, v, p)
    q = jnp.asarray(rng.randn(B, H, 3, D), jnp.float32)  # R=3 folded rows
    pos = jnp.full((B,), p_query, jnp.int32)
    np.testing.assert_allclose(
        np.asarray(flat.attend_rows(q, full, pos)),
        np.asarray(roll.attend_rows(q, ring, pos)), atol=1e-5)


def test_rolling_decode_matches_full_recompute():
    """Greedy rolling-ring decode == dense banded forward recomputed from
    scratch each step; the stream crosses the window boundary (t=12,
    +20 new = 32 total > W=16), so gather, wrap, and ring masking all
    execute."""
    params = _params(seed=5)
    prepared = gpt.prepare_stacked(params, CFG)
    apply_fn = llama.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(6), (2, 12), 0,
                             CFG.vocab_size)
    n_new = 20
    gen = llama.make_generate(CFG, max_new_tokens=n_new)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_rolling_decode_long_prompt():
    """Prompt itself longer than the window: the ring gather keeps only
    the live band of the prefill."""
    params = _params(seed=6)
    prepared = gpt.prepare_stacked(params, CFG)
    apply_fn = llama.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(7), (1, 24), 0,
                             CFG.vocab_size)
    n_new = 8
    got = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, ids, jax.random.PRNGKey(0)))
    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_rolling_int8_tracks_f32():
    params = _params(seed=7)
    prepared = gpt.prepare_stacked(params, CFG)
    ids = jax.random.randint(jax.random.PRNGKey(8), (2, 10), 0,
                             CFG.vocab_size)
    f32 = np.asarray(llama.make_generate(CFG, max_new_tokens=14)(
        prepared, ids, jax.random.PRNGKey(0)))
    i8 = np.asarray(llama.make_generate(CFG, max_new_tokens=14,
                                        kv_dtype="int8")(
        prepared, ids, jax.random.PRNGKey(0)))
    assert (i8 == f32).mean() >= 0.5, "int8 ring cache diverged wholesale"


def test_batcher_windowed_matches_solo_decode():
    """The batcher's window-masked slot pool == the solo rolling decode —
    two storage designs, one attention definition. Streams cross W."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = _params(seed=11)
    prepared = gpt.prepare_stacked(params, CFG)
    prompts = [np.array([5, 3, 7, 1, 2]), np.array([9, 8, 2])]
    n_new = 18  # 5 + 18 = 23 > W=16
    srv = ContinuousBatcher(
        CFG, prepared, slots=2, max_len=32, prompt_pad=8,
        family=llama.LlamaFamilyRows(CFG))
    rids = [srv.submit(p, max_new_tokens=n_new) for p in prompts]
    results = srv.drain()

    gen = llama.make_generate(CFG, max_new_tokens=n_new)
    for rid, p in zip(rids, prompts):
        want = np.asarray(gen(prepared, jnp.asarray(p, jnp.int32)[None, :],
                              jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[rid], want)


def test_paged_pool_serves_window_families():
    """Windowed families now ride the paged pool (PagedKV band-masks;
    the batcher reclaims rolled-out blocks — tests/test_paged.py pins
    the full parity/reclaim contract). Token parity vs the dense
    windowed batcher on a short stream here as the family-level pin."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = _params(seed=12)
    prepared = gpt.prepare_stacked(params, CFG)
    prompt = np.asarray([3, 1, 4, 1, 5, 9, 2, 6], np.int32)
    outs = {}
    for paged in (False, True):
        extra = dict(paged_blocks=12, block_len=8) if paged else {}
        srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=48,
                                prompt_pad=8,
                                family=llama.LlamaFamilyRows(CFG),
                                **extra)
        rid = srv.submit(prompt, max_new_tokens=24)  # past window=16
        srv.drain()
        outs[paged] = srv.results[rid]
    np.testing.assert_array_equal(outs[False], outs[True])


def test_seq_parallel_banded_ring_matches_dense():
    """Sliding-window configs now ride the BANDED ring on the
    sequence-parallel forward (parallel/ring_attention.py): the band's
    lower bound masks per ring block and out-of-window hops are skipped
    — logits must match the dense band-masked forward. At t=32 over a
    4-ring, t_local=8 and window=16 gives live hops
    ceil(15/8)+1 = 3 < 4, so the hop-skip is genuinely exercised."""
    from dnn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"seq": 4})
    params = _params(seed=13)
    prepared = gpt.prepare_stacked(params, CFG)
    t = 32  # window 16 spans 3 of the 4 shards' blocks
    ids = np.random.RandomState(14).randint(0, CFG.vocab_size, (2, t))
    want = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    got = np.asarray(llama.make_apply_seq_parallel(CFG, mesh)(
        prepared, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_seq_sharded_decode_rejects_window():
    from dnn_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"seq": 2})
    with pytest.raises(ValueError, match="sliding-window"):
        llama.make_generate_seq_sharded(CFG, mesh, max_new_tokens=4)


def test_mistral_preset_registered():
    from dnn_tpu.registry import get_model

    spec = get_model("mistral-7b")
    assert spec.config.sliding_window == 4096
    assert spec.config.n_kv_head == 8
