"""MoE FFN + expert parallelism.

Key invariants: (1) routing respects top_k and capacity; (2) the dense
grouped path equals a slow per-token reference; (3) the expert-parallel
shard_map path (all_to_all dispatch over the "expert" mesh axis) equals
the dense path with groups == n_devices — the parity contract that makes
EP a placement decision, not a semantics change; (4) the layer is
differentiable (it trains)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.ops.nn import gelu
from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh
from dnn_tpu.parallel.moe import (
    init_moe,
    load_balance_loss,
    make_moe_ffn_ep,
    moe_capacity,
    moe_ffn,
    route_topk,
)

D, E, F = 16, 4, 32


@pytest.fixture(scope="module")
def moe_params():
    return init_moe(jax.random.PRNGKey(0), D, E, F)


def test_route_topk_respects_k_and_capacity():
    s, cap, k = 32, 3, 2
    logits = jax.random.normal(jax.random.PRNGKey(1), (s, E))
    dispatch, combine, aux = route_topk(logits, top_k=k, capacity=cap)
    d = np.asarray(dispatch)
    # each token occupies at most k slots, each slot at most one token-weight
    assert d.sum(axis=(1, 2)).max() <= k
    assert d.max() == 1.0
    # no expert slot is double-booked
    assert d.sum(axis=0).max() <= 1.0 + 1e-6
    # capacity: at most cap tokens land on any expert
    assert d.sum(axis=(0, 2)).max() <= cap
    # combine weights live exactly on dispatched slots
    c = np.asarray(combine)
    assert ((c > 0) <= (d > 0)).all()
    # kept tokens' weights are normalized over their kept experts
    kept_w = c.sum(axis=(1, 2))
    full = d.sum(axis=(1, 2)) == k  # tokens with all k slots kept
    np.testing.assert_allclose(kept_w[full], 1.0, rtol=1e-5)
    assert aux["load"].shape == (E,) and aux["importance"].shape == (E,)


def test_route_deterministic_order():
    logits = jax.random.normal(jax.random.PRNGKey(2), (16, E))
    a = route_topk(logits, top_k=2, capacity=4)[0]
    b = route_topk(logits, top_k=2, capacity=4)[0]
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def _slow_reference(params, x, *, top_k, capacity):
    """Per-token numpy re-implementation of grouped routing + expert FFN
    (groups=1). Independent code path: loops, no one-hot einsums."""
    xt = np.asarray(x, np.float64).reshape(-1, x.shape[-1])
    router = np.asarray(params["router"]["kernel"], np.float64)
    wi, bi = np.asarray(params["wi"], np.float64), np.asarray(params["bi"], np.float64)
    wo, bo = np.asarray(params["wo"], np.float64), np.asarray(params["bo"], np.float64)
    logits = xt @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)

    s = xt.shape[0]
    counts = np.zeros(E, int)
    assign = [[] for _ in range(s)]  # (expert, weight) kept pairs
    rem = probs.copy()
    for _ in range(top_k):
        sel = rem.argmax(-1)
        for t in range(s):
            e = sel[t]
            if counts[e] < capacity:
                assign[t].append((e, probs[t, e]))
            counts[e] += 1
        # recompute counts pass-by-round like route_topk: positions count
        # every selection, kept or not — replicate by NOT rolling back
        for t in range(s):
            rem[t, sel[t]] = 0.0
    y = np.zeros_like(xt)
    for t in range(s):
        wsum = sum(w for _, w in assign[t])
        if wsum <= 0:
            continue
        for e, w in assign[t]:
            h = xt[t] @ wi[e] + bi[e]
            h = np.asarray(gelu(jnp.asarray(h, jnp.float32)), np.float64)
            o = h @ wo[e] + bo[e]
            y[t] += (w / wsum) * o
    return y.reshape(x.shape)


def test_dense_matches_slow_reference(moe_params):
    """The einsum dispatch path == an independent per-token loop."""
    b, t = 2, 8
    x = jax.random.normal(jax.random.PRNGKey(3), (b, t, D), jnp.float32)
    cap = moe_capacity(b * t, E, 2, 1.25)
    got = np.asarray(moe_ffn(moe_params, x, top_k=2, capacity_factor=1.25, groups=1))
    want = _slow_reference(moe_params, x, top_k=2, capacity=cap)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_overflow_drops_to_zero(moe_params):
    """With capacity 1 and many tokens, overflow tokens produce zero output
    (callers' residual passes them through)."""
    x = jnp.ones((1, 16, D))  # identical tokens -> identical routing -> overflow
    y = moe_ffn(moe_params, x, top_k=1, capacity_factor=1.0 / 16.0)
    yn = np.asarray(y)
    # capacity is 1: exactly one token per selected expert got computed
    nonzero = (np.abs(yn.reshape(16, D)).sum(-1) > 1e-6).sum()
    assert nonzero <= 2  # top-1 of identical tokens: <= 1 expert used (+fp ties)


@pytest.mark.parametrize("n_dev", [2, 4])
def test_ep_matches_dense(moe_params, n_dev):
    """shard_map EP over the expert axis == dense grouped path, exactly
    (same routing groups, same capacity)."""
    mesh = make_mesh({EXPERT_AXIS: n_dev}, jax.devices()[:n_dev])
    b, t = n_dev * 2, 4
    x = jax.random.normal(jax.random.PRNGKey(4), (b, t, D), jnp.float32)
    dense = np.asarray(moe_ffn(moe_params, x, top_k=2, groups=n_dev))
    ep_fn = make_moe_ffn_ep(mesh, top_k=2)
    ep = np.asarray(jax.jit(ep_fn)(moe_params, x))
    np.testing.assert_allclose(ep, dense, rtol=1e-5, atol=1e-6)


def test_ep_grad_flows(moe_params):
    """The EP layer trains: grads flow through routing + all_to_all."""
    mesh = make_mesh({EXPERT_AXIS: 2}, jax.devices()[:2])
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 4, D), jnp.float32)
    ep_fn = make_moe_ffn_ep(mesh, top_k=2)

    def loss(p):
        return jnp.mean(ep_fn(p, x) ** 2)

    g = jax.grad(loss)(moe_params)
    gn = sum(float(jnp.abs(l).sum()) for l in jax.tree.leaves(g))
    assert np.isfinite(gn) and gn > 0
    # expert weights receive gradient
    assert float(jnp.abs(g["wi"]).sum()) > 0


def test_load_balance_loss_uniform_is_one():
    aux = {"load": jnp.full((E,), 1.0 / E), "importance": jnp.full((E,), 1.0 / E)}
    np.testing.assert_allclose(float(load_balance_loss(aux)), 1.0, rtol=1e-6)


def test_load_normalized_by_top_k():
    """aux['load'] is the fraction of SELECTIONS (normalized by k*S): under
    perfectly uniform top-2 routing every expert reports 1/E, so the
    balance loss's 1.0 floor holds for any k — the k=2 case the formula's
    docstring promises."""
    s = E  # one token per expert
    # token i strongly prefers expert i, second-prefers expert (i+1) % E
    logits = jnp.log(jnp.eye(E) * 8 + jnp.roll(jnp.eye(E), 1, axis=1) * 4 + 1e-4)
    dispatch, _, aux = route_topk(logits, top_k=2, capacity=2)
    assert np.asarray(dispatch).sum() == 2 * s  # nothing dropped
    np.testing.assert_allclose(np.asarray(aux["load"]), 1.0 / E, atol=1e-6)
