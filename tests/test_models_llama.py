"""LLaMA family tests.

Parity is tested three ways, mirroring the GPT family's strategy:
HF/torch LlamaForCausalLM == our forward on converted weights (the
weight-compat contract for real checkpoints), partition composition ==
full model, and incremental KV-cache decode == repeated full forwards.
GQA specifics get their own checks: the cache must hold KV heads (not H),
and a 2-stage pipeline of the partitioned model must match the solo run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import llama
from dnn_tpu.registry import get_model

CFG = llama.PRESETS["llama-test"]  # L=4, H=4, KV=2, C=64, ff=128, V=256


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_to_hf_config_overrides_win():
    """Explicit overrides must replace the mapping's defaults (the
    documented pass-through contract), not collide with them."""
    transformers = pytest.importorskip("transformers")

    c = llama.to_hf_config(CFG, attention_bias=True)
    assert isinstance(c, transformers.LlamaConfig)
    assert c.attention_bias is True


def test_hf_llama_logit_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.LlamaConfig)
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    # ranking parity is the bar that matters for decode
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))


def test_stacked_matches_per_layer():
    from dnn_tpu.models import gpt

    params = _params()
    prepared = gpt.prepare_stacked(params, CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, CFG.vocab_size)
    a = llama.make_apply(CFG)(params, ids)
    b = llama.make_apply_stacked(CFG)(prepared, ids)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


@pytest.mark.parametrize("parts", [1, 2, 4])
def test_partition_composes_to_full_model(parts):
    params = _params(seed=2)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, CFG.vocab_size)
    want = np.asarray(llama.make_apply(CFG)(params, ids))
    stages = llama.make_partition(CFG)(parts)
    x = ids
    for st in stages:
        x = st.apply(st.slice_params(params), x)
    np.testing.assert_allclose(np.asarray(x), want, atol=1e-4, rtol=1e-4)


def test_registry_and_pipeline():
    spec = get_model("llama-test")
    assert spec.config is CFG
    params = spec.init(jax.random.PRNGKey(4))
    ids = np.asarray(spec.example_input(batch_size=2, seq_len=8))

    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.parallel.pipeline import spmd_pipeline

    stages = spec.partition(2)
    mesh = make_mesh({STAGE_AXIS: 2}, jax.devices()[:2])
    got = spmd_pipeline(
        [st.apply for st in stages],
        [st.slice_params(params) for st in stages],
        jnp.asarray(ids), mesh=mesh, num_microbatches=2,
        param_placement="replicated",
    )
    want = spec.apply(params, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)


def test_cache_holds_kv_heads_not_q_heads():
    cache = llama.init_cache(CFG, 2, 16)
    assert cache["k"].shape == (CFG.n_layer, 2, CFG.n_kv_head, 16,
                                CFG.head_dim), cache["k"].shape
    i8 = llama.init_cache(CFG, 2, 16, "int8")
    assert i8["k"].dtype == jnp.int8
    assert i8["ks"].shape == (CFG.n_layer, 2, CFG.n_kv_head, 16)


def test_incremental_decode_matches_full_recompute():
    params = _params(seed=5)
    from dnn_tpu.models import gpt

    prepared = gpt.prepare_stacked(params, CFG)
    apply_fn = llama.make_apply(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, CFG.vocab_size)
    n_new = 6
    gen = llama.make_generate(CFG, max_new_tokens=n_new)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_int8_cache_decode_tracks_f32():
    params = _params(seed=7)
    from dnn_tpu.models import gpt

    prepared = gpt.prepare_stacked(params, CFG)
    ids = jax.random.randint(jax.random.PRNGKey(8), (2, 8), 0, CFG.vocab_size)
    f32 = np.asarray(llama.make_generate(CFG, max_new_tokens=10)(
        prepared, ids, jax.random.PRNGKey(0)))
    i8 = np.asarray(llama.make_generate(CFG, max_new_tokens=10,
                                        kv_dtype="int8")(
        prepared, ids, jax.random.PRNGKey(0)))
    assert (i8 == f32).mean() >= 0.5, "int8 cache diverged wholesale"


def test_quantized_weights_keep_ranking():
    from dnn_tpu.quant import quantize_tree

    params = _params(seed=9)
    q = quantize_tree(params)
    ids = jax.random.randint(jax.random.PRNGKey(10), (2, 10), 0, CFG.vocab_size)
    a = np.asarray(llama.make_apply(CFG)(params, ids)).astype(np.float64)
    b = np.asarray(llama.make_apply(CFG)(q, ids)).astype(np.float64)
    cos = (a.ravel() @ b.ravel()) / (np.linalg.norm(a) * np.linalg.norm(b))
    assert cos > 0.999, f"quantized llama cosine {cos}"


def test_llama_batcher_matches_solo_decode():
    """A greedy LLaMA slot in the continuous-batching pool == a solo
    batch-1 run — the family-adapter contract (LlamaFamilyRows)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = _params(seed=11)
    prepared = gpt.prepare_stacked(params, CFG)
    prompts = [np.array([5, 3, 7, 1, 2]), np.array([9, 8, 2])]
    n_new = 6
    srv = ContinuousBatcher(
        CFG, prepared, slots=2, max_len=32, prompt_pad=8,
        family=llama.LlamaFamilyRows(CFG))
    rids = [srv.submit(p, max_new_tokens=n_new) for p in prompts]
    results = srv.drain()

    gen = llama.make_generate(CFG, max_new_tokens=n_new)
    for rid, p in zip(rids, prompts):
        want = np.asarray(gen(prepared, jnp.asarray(p, jnp.int32)[None, :],
                              jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[rid], want)


def test_llama_batcher_int8_cache():
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.serving import ContinuousBatcher

    params = _params(seed=12)
    prepared = gpt.prepare_stacked(params, CFG)
    prompt = np.array([4, 5, 6, 7])
    srv = ContinuousBatcher(
        CFG, prepared, slots=2, max_len=32, prompt_pad=8, kv_dtype="int8",
        family=llama.LlamaFamilyRows(CFG))
    rid = srv.submit(prompt, max_new_tokens=6)
    got = srv.drain()[rid]
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=6,
                                          kv_dtype="int8")(
        prepared, jnp.asarray(prompt, jnp.int32)[None, :],
        jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_llama_pipeline_training_loss_matches_single_program(devices):
    """The LLaMA family trains through the pipeline schedules like its GPT
    sibling: GPipe loss == the single-program next-token loss on the same
    batch (embed/blocks/head plug into make_pipeline_train_step)."""
    import optax

    from dnn_tpu import train
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh

    params = _params(seed=13)
    n_stages, per = 2, CFG.n_layer // 2
    mesh = make_mesh({STAGE_AXIS: n_stages}, devices[:n_stages])
    stacks = [gpt.stack_blocks(params, range(s * per, (s + 1) * per))
              for s in range(n_stages)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *stacks)
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    tokens = jax.random.randint(jax.random.PRNGKey(14), (4, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    opt = optax.sgd(1e-3)
    step = train.make_pipeline_train_step(
        lambda bp, h: llama.blocks_scan(bp, h, cfg=CFG, compute_dtype=None),
        lambda a, ids: llama.embed(a, ids, cfg=CFG),
        lambda a, h: llama.head(a, h.astype(jnp.float32), cfg=CFG),
        opt, mesh, num_microbatches=2,
    )
    _, _, _, loss = step(stacked, aux, (opt.init(stacked), opt.init(aux)),
                         tokens)
    want = train.next_token_loss(llama.make_apply(CFG), params, tokens)
    assert float(loss) == pytest.approx(float(want), rel=1e-4)


def test_llama_pipeline_generate_matches_solo(devices):
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    params = _params(seed=15)
    prepared = gpt.prepare_stacked(params, CFG)
    mesh = make_mesh({STAGE_AXIS: 2}, devices[:2])
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    ids = jax.random.randint(jax.random.PRNGKey(16), (2, 6), 0, CFG.vocab_size)
    gen = llama.make_pipeline_generate(CFG, mesh, max_new_tokens=5)
    got = np.asarray(gen(stage_blocks, aux, ids, jax.random.PRNGKey(0)))
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=5)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_llama_speculative_greedy_parity():
    """Speculative decoding with a LLaMA target: greedy output must equal
    target-only decode — including CROSS-FAMILY, a GPT-2 draft proposing
    for a LLaMA target (same vocab is the only requirement)."""
    from dnn_tpu.models import gpt
    from dnn_tpu.runtime.speculative import make_speculative_generate

    params = _params(seed=17)
    t_prep = gpt.prepare_stacked(params, CFG)
    ids = jax.random.randint(jax.random.PRNGKey(18), (1, 8), 0, CFG.vocab_size)
    n = 10
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=n)(
        t_prep, ids, jax.random.PRNGKey(0)))

    # llama draft (same family, same tiny model as its own draft)
    spec_ll = make_speculative_generate(CFG, CFG, max_new_tokens=n, k=3)
    got = np.asarray(spec_ll(t_prep, t_prep, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)

    # cross-family: gpt2-test draft (vocab 256 matches llama-test)
    g_cfg = gpt.PRESETS["gpt2-test"]
    g_prep = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(19), g_cfg), g_cfg)
    spec_x = make_speculative_generate(CFG, g_cfg, max_new_tokens=n, k=3)
    got_x = np.asarray(spec_x(t_prep, g_prep, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got_x, want)


def test_llama_tensor_parallel_train_step(devices):
    """dp x tp training for LLaMA via the generic Megatron spec table:
    sharded-step loss == the single-program next-token loss."""
    import optax

    from dnn_tpu import train
    from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh

    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 2}, devices[:4])
    apply_fn = llama.make_apply(CFG)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    p_sh, specs = train.init_sharded(
        lambda rng: llama.init(rng, CFG), jax.random.PRNGKey(20), mesh)
    opt = optax.sgd(1e-3)
    sstep = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    tokens = jax.random.randint(jax.random.PRNGKey(21), (4, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    p1, _, loss = sstep(p_sh, opt.init(p_sh), tokens)
    jax.block_until_ready(p1)

    # reference params = the SHARDED init's values, gathered — not a
    # fresh llama.init: under this jax's legacy (non-partitionable)
    # threefry, jit-with-out_shardings generates different random values
    # than the un-jitted init (see train.init_sharded's docstring), and
    # this test pins TRAIN-STEP parity, not RNG-partitioning semantics
    params = jax.tree.map(np.asarray, p_sh)
    want = train.next_token_loss(apply_fn, params, tokens)
    assert float(loss) == pytest.approx(float(want), rel=1e-4)


@pytest.mark.parametrize("n", [2, 4])
def test_llama_seq_parallel_matches_dense(n, devices):
    """Ring attention with GQA-narrow K/V blocks == the dense forward."""
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh

    params = _params(seed=22)
    prepared = gpt.prepare_stacked(params, CFG)
    mesh = make_mesh({SEQ_AXIS: n}, devices[:n])
    ids = jax.random.randint(jax.random.PRNGKey(23), (2, 4 * n), 0,
                             CFG.vocab_size)
    got = llama.make_apply_seq_parallel(CFG, mesh)(prepared, ids)
    want = llama.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_llama_pipeline_generate_int8_cache(devices):
    """LLaMA pipeline decode with int8 cache shards (GQA group fold over
    the quantized codec, scale leaves riding the ring's where-merge) ==
    solo int8 decode."""
    from dnn_tpu.models import gpt
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    params = _params(seed=33)
    prepared = gpt.prepare_stacked(params, CFG)
    mesh = make_mesh({STAGE_AXIS: 2}, devices[:2])
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    ids = jax.random.randint(jax.random.PRNGKey(34), (2, 5), 0, CFG.vocab_size)
    gen = llama.make_pipeline_generate(CFG, mesh, max_new_tokens=5,
                                       kv_dtype="int8")
    got = np.asarray(gen(stage_blocks, aux, ids, jax.random.PRNGKey(0)))
    want = np.asarray(llama.make_generate(CFG, max_new_tokens=5,
                                          kv_dtype="int8")(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
