"""Static HLO bytes audit (utils/hlo_audit.py): parser pins for both
program-text formats, plus the decode-step regressions that answer
BASELINE.md's long-context hypotheses on paper —

  (a) cache-sized TRANSPOSE: absent at the StableHLO level for every
      decode step (the program never demands a transposed cache copy);
  (b) cache-sized COPY: present in the backend-optimized unbucketed step
      (the scan/carry structure materializes cache-scale buffers), and
      ABSENT at allocation scale in the bucketed step — bucketing bounds
      every materialized buffer by the live bucket, not max_len."""

import jax
import jax.numpy as jnp

from dnn_tpu.models import gpt
from dnn_tpu.utils import hlo_audit as H

CFG = gpt.GPTConfig(block_size=256, vocab_size=128, n_layer=2, n_head=2,
                    n_embd=32)


def test_parser_stablehlo_format():
    text = """
    %3 = stablehlo.transpose %2, dims = [0, 1, 3, 2] : (tensor<8x12x256x64xf32>) -> tensor<8x12x64x256xf32>
    %4 = stablehlo.add %3, %3 : tensor<8x12x64x256xf32>
    %5 = stablehlo.constant dense<0.0> : tensor<f32>
    """
    rows = H.op_result_sizes(text)
    assert ("transpose", 8 * 12 * 256 * 64) in rows
    assert ("add", 8 * 12 * 256 * 64) in rows
    assert ("constant", 1) in rows
    assert H.count_cache_sized(text, 8 * 12 * 256 * 64) == {"transpose": 1}


def test_parser_hlo_format():
    text = """
    %copy.1 = f32[4,8,12,512,64]{4,3,2,1,0} copy(f32[4,8,12,512,64]{4,3,2,1,0} %p.1)
    %transpose.2 = bf16[8,12,64,512]{3,2,1,0} transpose(bf16[8,12,512,64]{3,2,1,0} %p.2), dimensions={0,1,3,2}
    %add.3 = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)
    """
    counts = H.count_cache_sized(text, 8 * 12 * 512 * 64)
    assert counts == {"copy": 1, "transpose": 1}
    assert H.count_cache_sized(text, 10 ** 12) == {}


def _steps():
    alloc = 256  # the serving allocation (max_len)
    bucket = 32  # a live bucket at position << alloc
    step_u, args_u, layer_alloc = H.gpt_decode_step(CFG, batch=2,
                                                    s_max=alloc)
    step_b, args_b, _ = H.gpt_decode_step(CFG, batch=2, s_max=bucket)
    return (step_u, args_u), (step_b, args_b), layer_alloc


def test_stablehlo_demands_no_cache_sized_transpose_or_copy():
    """Hypothesis (a) at the program level: the traced decode step never
    asks for a transposed/copied cache — for the unbucketed AND bucketed
    programs alike."""
    (step_u, args_u), (step_b, args_b), layer_alloc = _steps()
    assert H.audit_decode_step(step_u, args_u, layer_alloc)["total"] == 0
    assert H.audit_decode_step(step_b, args_b, layer_alloc)["total"] == 0


def test_optimized_unbucketed_step_materializes_cache_scale_copies():
    """Hypothesis (b) on this host's backend: the compiled unbucketed
    decode step carries cache-scale copies (scan-carry materialization)
    — the structural 2x+ traffic multiplier the bucketed program bounds.
    Count > 0 is the finding, not a bug: it is recorded in BASELINE.md
    as the CPU-lowering answer to the 13%-MBU question."""
    (step_u, args_u), _, layer_alloc = _steps()
    out = H.audit_decode_step(step_u, args_u, layer_alloc, optimize=True)
    assert out["counts"].get("transpose", 0) == 0  # (a) stays dead
    assert out["counts"].get("copy", 0) > 0        # (b) confirmed


def test_optimized_bucketed_step_materializes_nothing_allocation_sized():
    """THE bucketing regression: at a live bucket << max_len, no buffer
    of allocation scale (one max_len cache layer or bigger) appears in
    the compiled step — every materialization is bounded by the bucket."""
    _, (step_b, args_b), layer_alloc = _steps()
    out = H.audit_decode_step(step_b, args_b, layer_alloc, optimize=True)
    assert out["total"] == 0, (
        f"bucketed decode step materialized allocation-sized buffers: "
        f"{out['counts']}")


def test_eval_shape_costs_no_memory():
    """The audit rides abstract shapes end-to-end: a 1B-scale config
    lowers without building weights (only the StableHLO level — no
    backend compile — so this stays fast in CI)."""
    big = gpt.GPTConfig(block_size=2048, vocab_size=50257, n_layer=24,
                        n_head=16, n_embd=1024)
    step, args, layer = H.gpt_decode_step(big, batch=8, s_max=2048,
                                          compute_dtype=jnp.bfloat16,
                                          kv_dtype=jnp.bfloat16)
    out = H.audit_decode_step(step, args, layer)
    assert out["total"] == 0
    assert out["backend"] == "none (StableHLO)"
