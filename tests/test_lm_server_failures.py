"""LM daemon failure-path tests: a wedged/dying device worker must fail
requests fast with UNAVAILABLE — never leave clients hanging for the full
request timeout (the resilience contract added after round-2 review)."""

import time

import grpc
import jax
import numpy as np
import pytest

from dnn_tpu.comm.client import NodeClient
from dnn_tpu.models import gpt
from dnn_tpu.runtime.lm_server import _BatcherWorker
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


def test_worker_death_fails_pending_futures_fast():
    """A device-side error in step() resolves every pending future with a
    RuntimeError instead of leaving them to time out."""
    srv = ContinuousBatcher(CFG, _prepared(), slots=2, max_len=32,
                            prompt_pad=8)

    calls = {"n": 0}
    real_step = srv.step

    def exploding_step():
        calls["n"] += 1
        if calls["n"] >= 2:
            raise RuntimeError("injected device fault")
        return real_step()

    srv.step = exploding_step
    worker = _BatcherWorker(srv)
    worker.start()
    fut = worker.submit(np.array([1, 2, 3], np.int32), 8, None)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="worker died"):
        fut.result(timeout=60)
    assert time.monotonic() - t0 < 30, "future resolved too slowly"
    worker.join(timeout=10)
    assert not worker.is_alive()

    # submits AFTER death fail immediately (the dead-marking lock path)
    fut2 = worker.submit(np.array([4, 5], np.int32), 4, None)
    with pytest.raises(RuntimeError):
        fut2.result(timeout=5)


def test_dead_worker_surfaces_unavailable_over_grpc():
    """End-to-end over the wire: kill the worker, then HealthCheck reports
    unhealthy and SendTensor aborts UNAVAILABLE instead of hanging."""
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    port = 59311
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=1), port=port, slots=2, max_len=32,
        prompt_pad=8, default_max_new=4)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        assert c.health_check()
        # one good request proves the path, then kill the worker thread
        out = c.generate(np.array([1, 2, 3], np.int32), max_new_tokens=3)
        assert out.shape == (3,)

        # the background helper hides the servicer in its closure; find
        # the live worker via thread enumeration and kill it abruptly
        import threading

        workers = [th for th in threading.enumerate()
                   if th.name == "lm-batcher"]
        assert workers, "no lm-batcher thread found"
        for w in workers:
            # simulate sudden device death: poison the queue path by
            # marking dead exactly as a step() crash would
            with w._lock:
                w._dead = RuntimeError("injected: device gone")
            w._abandon = True
            w._stop_evt.set()
        for w in workers:
            w.join(timeout=10)

        assert not c.health_check(), "dead worker must report unhealthy"
        t0 = time.monotonic()
        with pytest.raises((grpc.RpcError, RuntimeError)):
            c.generate(np.array([1, 2], np.int32), max_new_tokens=3)
        assert time.monotonic() - t0 < 30, "dead-worker request not fast-failed"
        c.close()
    finally:
        stop()


def test_stop_drain_true_fails_late_submits_fast():
    """A submit racing (or following) a drain shutdown must fail fast, not
    enqueue a future the exited worker will never resolve."""
    srv = ContinuousBatcher(CFG, _prepared(seed=3), slots=1, max_len=32,
                            prompt_pad=8)
    worker = _BatcherWorker(srv)
    worker.start()
    fut = worker.submit(np.array([1, 2, 3], np.int32), 4, None)
    worker.stop(drain=True)
    # the pre-stop submit still drains to a real result
    assert fut.result(timeout=60).shape == (4,)
    worker.join(timeout=20)
    assert not worker.is_alive()
    # a post-stop submit resolves immediately with shutdown, not a hang
    t0 = time.monotonic()
    fut2 = worker.submit(np.array([4, 5], np.int32), 4, None)
    with pytest.raises(RuntimeError, match="shutting down"):
        fut2.result(timeout=5)
    assert time.monotonic() - t0 < 5


def test_out_of_range_prompt_ids_rejected_over_grpc():
    """Raw-id prompts outside [0, vocab_size) must abort INVALID_ARGUMENT
    instead of silently gathering edge-of-table embeddings."""
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    port = 59317
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=4), port=port, slots=1, max_len=32,
        prompt_pad=8, default_max_new=4)
    try:
        c = NodeClient(f"127.0.0.1:{port}")
        for bad in (np.array([0, CFG.vocab_size], np.int32),
                    np.array([-1, 2], np.int32)):
            with pytest.raises(grpc.RpcError) as ei:
                c.generate(bad, max_new_tokens=2)
            assert ei.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        # boundary ids are fine
        ok = c.generate(np.array([0, CFG.vocab_size - 1], np.int32),
                        max_new_tokens=2)
        assert ok.shape == (2,)
        c.close()
    finally:
        stop()


def test_submit_rejects_nonpositive_budget():
    srv = ContinuousBatcher(CFG, _prepared(seed=5), slots=1, max_len=32,
                            prompt_pad=8)
    for bad in (0, -3):
        with pytest.raises(ValueError, match="max_new_tokens"):
            srv.submit(np.array([1, 2], np.int32), max_new_tokens=bad)


def test_stop_drain_false_cancels_quickly():
    """Non-drain shutdown abandons an in-flight long generation instead of
    stepping the device to completion."""
    srv = ContinuousBatcher(CFG, _prepared(seed=2), slots=1,
                            max_len=CFG.block_size, prompt_pad=8)
    worker = _BatcherWorker(srv)
    worker.start()
    fut = worker.submit(np.array([1, 2, 3], np.int32), 50, None)
    # let it get admitted and step a little
    time.sleep(1.0)
    worker.stop(drain=False)
    worker.join(timeout=20)
    assert not worker.is_alive(), "worker kept stepping after abandon"
    assert fut.cancelled() or fut.done()
