"""Smoke tests for the driver entry points (__graft_entry__.py) on the
virtual 8-device CPU mesh."""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import __graft_entry__ as graft  # noqa: E402


@pytest.mark.parametrize("n", [2, 8])
def test_dryrun_multichip(n):
    graft.dryrun_multichip(n)


def test_entry_shapes():
    fn, args = graft.entry()
    prepared, ids = args
    assert ids.shape == (1, 128)
    # don't compile gpt2-small in the unit suite; just check traceability
    import jax

    out = jax.eval_shape(fn, prepared, ids)
    assert out.shape == (1, 128, 50257)
