"""OLMo-2 family: the POST-norm-only block — attention and the MLP read
the RAW residual stream, each branch output RMS-norms before its
residual add (no ln_1/ln_2 leaves at all) — plus full-projection-width
q/k norms (qk_norm_width="proj": the merged (H*D,) vector norms jointly
across heads, unlike Qwen3's per-head norm).

Both switches ride the shared helpers (_pre_normed, _qk_normed), so the
dense forward, cached decode, and batcher rows inherit them — pinned
against HF Olmo2ForCausalLM and the framework's own contracts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama

CFG = llama.PRESETS["olmo2-test"]  # L=4, GQA 2:1, post-norm-only


def _params(seed=0):
    return llama.init(jax.random.PRNGKey(seed), CFG)


def test_structure():
    p = _params()
    blk = p["h_0"]
    assert "ln_1" not in blk and "ln_2" not in blk
    assert "post_ln_1" in blk and "post_ln_2" in blk
    d = CFG.head_dim
    assert blk["attn"]["q_norm"]["scale"].shape == (CFG.n_head * d,)
    assert blk["attn"]["k_norm"]["scale"].shape == (CFG.n_kv_head * d,)


def test_config_validation():
    import dataclasses

    with pytest.raises(ValueError, match="post_norms"):
        dataclasses.replace(CFG, post_norms=False)
    with pytest.raises(ValueError, match="qk_norm_width"):
        dataclasses.replace(CFG, qk_norm_width="banana")


def test_hf_olmo2_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.Olmo2Config)
    torch.manual_seed(0)
    model = transformers.Olmo2ForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    assert not any("input_layernorm" in k for k in sd)

    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd, post_norms=True)
    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy cached decode == HF generate (raw-stream branches + proj
    # -width qk norms at every step)
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 10))
    n_new = 12
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 10:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama.make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_batcher_matches_solo():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(seed=3)
    prepared = gpt.prepare_stacked(p, CFG)
    prompts = [np.asarray([3, 1, 4, 1, 5]), np.asarray([9, 2, 6])]
    n_new = 7
    solo = llama.make_generate(CFG, max_new_tokens=n_new)
    want = [np.asarray(solo(prepared, jnp.asarray(pr[None]),
                            jax.random.PRNGKey(0)))[0] for pr in prompts]
    srv = ContinuousBatcher(CFG, prepared, slots=2,
                            max_len=CFG.block_size, prompt_pad=8,
                            family=llama.LlamaFamilyRows(CFG))
    rids = [srv.submit(pr, max_new_tokens=n_new) for pr in prompts]
    srv.drain()
    for rid, w in zip(rids, want):
        np.testing.assert_array_equal(srv.results[rid], w)


def test_torch_export_round_trips():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    from dnn_tpu.io.torch_export import llama_state_dict_from_params

    p = _params(seed=4)
    sd = llama_state_dict_from_params(p)
    assert "model.layers.0.post_feedforward_layernorm.weight" in sd
    assert "model.layers.0.input_layernorm.weight" not in sd
    model = transformers.Olmo2ForCausalLM(
        llama.to_hf_config(CFG, attn_implementation="eager")).eval()
    missing, unexpected = model.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd.items()}, strict=False)
    assert not unexpected, unexpected
    ids = np.random.RandomState(5).randint(0, CFG.vocab_size, (2, 10))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama.make_apply(CFG)(p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-3, rtol=2e-3)


def test_registry_registered():
    from dnn_tpu.registry import get_model

    spec = get_model("olmo2-7b")
    assert not spec.config.pre_norm and spec.config.qk_norm
