"""Fixture suite for the trace/shard-safety analyzer (dnn_tpu/analysis).

One known-bad snippet per rule ID (must be flagged) and one known-good
twin (must not be), plus: the self-lint gate (the repo is clean modulo
analysis/baseline.json, and every baseline entry still fires and is
justified), fingerprint stability under line drift, the jaxpr program
checks (PRG001/2/3/4) on hand-built programs, and the CLI exit-code
contract — 0 on HEAD, nonzero when a fixture hazard is injected.
"""

import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dnn_tpu.analysis.findings import (
    diff_against_baseline,
    load_baseline,
)
from dnn_tpu.analysis.lint import lint_paths, lint_source

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG_DIR = os.path.join(REPO_ROOT, "dnn_tpu")
BASELINE = os.path.join(PKG_DIR, "analysis", "baseline.json")


def rules_of(src):
    return sorted({f.rule for f in lint_source(textwrap.dedent(src), "t")})


# ----------------------------------------------------------------------
# rule fixtures: (rule, known-bad, known-good twin)
# ----------------------------------------------------------------------

FIXTURES = {
    "TPU001": (
        """
        import jax
        @jax.jit
        def relu_bad(x):
            if x > 0:
                return x
            return -x
        """,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def relu_good(x):
            return jnp.where(x > 0, x, -x)
        """,
    ),
    "TPU002": (
        """
        import jax
        @jax.jit
        def loss_bad(x):
            return float(x.sum())
        """,
        """
        import jax
        def loss_good(x):
            # host conversion OUTSIDE the traced function is fine
            return float(x.sum())
        """,
    ),
    "TPU003": (
        """
        import jax
        def draws_bad():
            key = jax.random.PRNGKey(0)
            a = jax.random.normal(key, (4,))
            b = jax.random.uniform(key, (4,))
            return a, b
        """,
        """
        import jax
        def draws_good():
            key = jax.random.PRNGKey(0)
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1, (4,))
            b = jax.random.uniform(k2, (4,))
            return a, b
        """,
    ),
    "TPU004": (
        """
        import jax
        def _step(cache, tok):
            return cache
        step = jax.jit(_step, donate_argnums=(0,))
        def decode_bad(cache, tok):
            out = step(cache, tok)
            return cache.sum() + out.sum()
        """,
        """
        import jax
        def _step(cache, tok):
            return cache
        step = jax.jit(_step, donate_argnums=(0,))
        def decode_good(cache, tok):
            cache = step(cache, tok)
            return cache.sum()
        """,
    ),
    "TPU005": (
        """
        import jax
        def _step(cache, pos):
            return cache
        step = jax.jit(_step)
        def run_bad(cache, t):
            for i in range(8):
                cache = step(cache, t + i)
            return cache
        """,
        """
        import jax
        import jax.numpy as jnp
        def _step(cache, pos):
            return cache
        step = jax.jit(_step)
        def run_good(cache, t):
            for i in range(8):
                cache = step(cache, jnp.int32(t + i))
            return cache
        """,
    ),
    "TPU006": (
        """
        import jax
        from jax import lax
        def make(mesh):
            def body(x):
                return lax.cond(lax.axis_index('s') == 0,
                                lambda v: lax.psum(v, 's'),
                                lambda v: v, x)
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)
        """,
        """
        import jax
        from jax import lax
        def make(mesh):
            def body(x):
                return lax.cond(lax.axis_index('s') == 0,
                                lambda v: lax.psum(2 * v, 's'),
                                lambda v: lax.psum(v, 's'), x)
            return jax.shard_map(body, mesh=mesh, in_specs=None,
                                 out_specs=None)
        """,
    ),
}


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_rule_fixture_pair(rule):
    bad, good = FIXTURES[rule]
    assert rule in rules_of(bad), f"{rule} must flag its bad fixture"
    assert rules_of(good) == [], \
        f"{rule} good twin must be clean, got {rules_of(good)}"


# extra per-rule behaviors beyond the canonical pair -------------------

def test_tpu001_static_shape_branching_is_clean():
    src = """
    import jax
    @jax.jit
    def f(ids):
        b, t = ids.shape
        if t > 128:
            raise ValueError("too long")
        return ids * b
    """
    assert rules_of(src) == []


def test_tpu001_static_argnums_params_untainted():
    src = """
    import jax
    def _run(x, n):
        if n > 4:
            return x[:4]
        return x
    run = jax.jit(_run, static_argnums=(1,))
    """
    assert rules_of(src) == []


def test_tpu003_reuse_across_loop_iterations():
    src = """
    import jax
    def f(key):
        key = jax.random.PRNGKey(0)
        out = []
        for i in range(4):
            out.append(jax.random.normal(key, (2,)))
        return out
    """
    assert "TPU003" in rules_of(src)
    good = """
    import jax
    def f():
        key = jax.random.PRNGKey(0)
        out = []
        for i in range(4):
            key, sub = jax.random.split(key)
            out.append(jax.random.normal(sub, (2,)))
        return out
    """
    assert rules_of(good) == []


def test_tpu004_donation_in_loop_without_rebind():
    src = """
    import jax
    def _step(cache):
        return cache
    step = jax.jit(_step, donate_argnums=(0,))
    def run(cache):
        for _ in range(4):
            out = step(cache)
        return out
    """
    assert "TPU004" in rules_of(src)


def test_tpu005_static_argnums_in_loop():
    src = """
    import jax
    def _grow(cache, n):
        return cache
    grow = jax.jit(_grow, static_argnums=(1,))
    def run(cache):
        for i in range(16):
            cache = grow(cache, i * 2)
        return cache
    """
    assert "TPU005" in rules_of(src)


def test_tpu006_python_if_divergence():
    src = """
    import jax
    from jax import lax
    def make(mesh, flag):
        def body(x):
            if flag:
                x = lax.psum(x, 's')
            return x
        return jax.shard_map(body, mesh=mesh, in_specs=None,
                             out_specs=None)
    """
    assert "TPU006" in rules_of(src)


# ----------------------------------------------------------------------
# fingerprints + baseline + self-lint
# ----------------------------------------------------------------------

def test_fingerprint_survives_line_drift():
    src = FIXTURES["TPU001"][0]
    before = lint_source(textwrap.dedent(src), "m")
    shifted = "# pad\n# pad\n# pad\n" + textwrap.dedent(src)
    after = lint_source(shifted, "m")
    assert [f.fingerprint for f in before] == \
        [f.fingerprint for f in after]
    assert before[0].line != after[0].line


def test_self_lint_clean_modulo_baseline():
    """The repo's own package carries no unbaselined AST findings, and
    every baseline entry both still fires and says why it stays."""
    findings = lint_paths([PKG_DIR], repo_root=REPO_ROOT)
    entries = load_baseline(BASELINE)
    new, suppressed, stale = diff_against_baseline(findings, entries)
    assert not new, "unbaselined findings:\n" + "\n".join(
        f"{f.path}:{f.line} {f.rule} {f.message}" for f in new)
    lint_rules = {e["fingerprint"] for e in entries
                  if e["fingerprint"].startswith("TPU")}
    fired = {f.fingerprint for f in suppressed}
    assert lint_rules <= fired, \
        f"stale lint baseline entries: {lint_rules - fired}"


def test_baseline_requires_justification(tmp_path):
    p = tmp_path / "b.json"
    p.write_text(json.dumps({"suppressions": [
        {"fingerprint": "TPU001:x:abc"}]}))
    with pytest.raises(ValueError, match="justification"):
        load_baseline(p)


# ----------------------------------------------------------------------
# program pass (jaxpr checks)
# ----------------------------------------------------------------------

def _mesh2():
    return Mesh(np.array(jax.devices()[:2]), ("s",))


def test_prg001_divergent_cond_collectives_flagged():
    from dnn_tpu.analysis.program import check_branch_collectives

    mesh = _mesh2()

    def body(x):
        return lax.cond(lax.axis_index("s") == 0,
                        lambda v: lax.psum(v, "s"),
                        lambda v: v * 1.0, x)

    f = jax.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    closed = jax.make_jaxpr(f)(jnp.ones((4,)))
    findings = check_branch_collectives(closed, "fixture")
    assert any(f.rule == "PRG001" for f in findings)


def test_prg001_matched_cond_collectives_clean():
    from dnn_tpu.analysis.program import (
        check_branch_collectives,
        collective_signature,
    )

    mesh = _mesh2()

    def body(x):
        return lax.cond(lax.axis_index("s") == 0,
                        lambda v: lax.psum(2 * v, "s"),
                        lambda v: lax.psum(v, "s"), x)

    f = jax.shard_map(body, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False)
    closed = jax.make_jaxpr(f)(jnp.ones((4,)))
    assert check_branch_collectives(closed, "fixture") == []
    assert "psum" in collective_signature(closed)


def test_prg002_baked_constant_flagged():
    from dnn_tpu.analysis.program import baked_constants

    big = jnp.zeros((512, 1024))  # 2 MB closed-over constant

    def f(x):
        return x @ big

    closed = jax.make_jaxpr(f)(jnp.ones((4, 512)))
    assert any(f.rule == "PRG002"
               for f in baked_constants(closed, min_bytes=1 << 20))

    def g(w, x):  # same math, weights as an argument — clean
        return x @ w

    closed = jax.make_jaxpr(g)(big, jnp.ones((4, 512)))
    assert baked_constants(closed, min_bytes=1 << 20) == []


def test_prg003_donation_coverage():
    from dnn_tpu.analysis.program import donation_report

    def step(w, cache):
        return cache.at[0].set(w.sum())

    cache = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((8,), jnp.float32)
    rep = donation_report(step, (w, cache), (1,), where="fixture")
    assert rep["aliased"] == rep["expected"] == 1
    assert rep["findings"] == []

    def shrink(w, cache):  # output can never alias the donated input
        return cache[:1, :1]

    import warnings

    with warnings.catch_warnings():
        # the unusable-donation warning IS the condition under test
        warnings.simplefilter("ignore", UserWarning)
        rep = donation_report(shrink, (w, cache), (1,), where="fixture")
    assert any(f.rule == "PRG003" for f in rep["findings"])


def test_prg004_census_bound():
    from dnn_tpu.analysis.program import recompile_census

    shapes = [(jax.ShapeDtypeStruct((n, 4), jnp.float32),)
              for n in (1, 2, 3, 4)]
    rep = recompile_census(shapes, bound=2, where="fixture")
    assert rep["programs"] == 4
    assert any(f.rule == "PRG004" for f in rep["findings"])
    rep = recompile_census(shapes * 3, bound=4, where="fixture")
    assert rep["programs"] == 4 and rep["findings"] == []


def test_decode_audit_contract():
    """The real decode paths: full donation coverage, no cache-sized
    StableHLO transposes, bucketed census within the ladder bound, and
    the naive counterfactual correctly one-program-per-length."""
    from dnn_tpu.analysis.program import audit_decode_paths

    rep = audit_decode_paths(max_len=64)
    assert rep["findings"] == []
    assert rep["donation"]["aliased"] == rep["donation"]["expected"]
    assert rep["bucketed_census"]["programs"] <= len(rep["ladder"])
    assert rep["naive_census"]["programs"] == rep["naive_census"]["calls"]


def test_pipeline_audit_collectives_consistent():
    from dnn_tpu.analysis.program import audit_pipeline_programs

    rep = audit_pipeline_programs()
    assert rep.get("skipped") is None
    assert rep["findings"] == []
    # the GPipe loop: one hop ppermute + one last-stage psum, visible
    # in the traced program
    assert "ppermute" in rep["collective_signature"]
    assert "psum" in rep["collective_signature"]


def test_assert_collectives_consistent():
    """utils/audit.py's static triad leg: raises on divergent branches,
    passes on matched ones — without executing anything."""
    from dnn_tpu.utils.audit import assert_collectives_consistent

    mesh = _mesh2()

    def diverging(x):
        return lax.cond(lax.axis_index("s") == 0,
                        lambda v: lax.psum(v, "s"),
                        lambda v: v * 1.0, x)

    def matched(x):
        return lax.cond(lax.axis_index("s") == 0,
                        lambda v: lax.psum(2 * v, "s"),
                        lambda v: lax.psum(v, "s"), x)

    xs = jax.ShapeDtypeStruct((4,), jnp.float32)
    with pytest.raises(AssertionError, match="divergent collective"):
        assert_collectives_consistent(
            jax.shard_map(diverging, mesh=mesh, in_specs=P(),
                          out_specs=P(), check_vma=False), xs)
    assert_collectives_consistent(
        jax.shard_map(matched, mesh=mesh, in_specs=P(), out_specs=P(),
                      check_vma=False), xs)


# ----------------------------------------------------------------------
# CLI gate
# ----------------------------------------------------------------------

def test_cli_exits_zero_on_head():
    """The acceptance gate: the full analyzer (lint + program pass) runs
    clean on HEAD against the checked-in baseline."""
    from dnn_tpu.analysis.__main__ import main

    assert main([]) == 0


def test_cli_nonzero_on_injected_hazard(tmp_path, capsys):
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / "user_model.py"
    bad.write_text(textwrap.dedent(FIXTURES["TPU003"][0]))
    rc = main([str(bad), "--no-program", "--no-baseline"])
    assert rc == 1
    assert "TPU003" in capsys.readouterr().out

    good = tmp_path / "user_model_ok.py"
    good.write_text(textwrap.dedent(FIXTURES["TPU003"][1]))
    assert main([str(good), "--no-program", "--no-baseline"]) == 0


@pytest.mark.parametrize("rule", sorted(FIXTURES))
def test_cli_nonzero_per_rule(rule, tmp_path):
    """Every rule's bad fixture, injected as user code, fails the gate."""
    from dnn_tpu.analysis.__main__ import main

    bad = tmp_path / f"inject_{rule.lower()}.py"
    bad.write_text(textwrap.dedent(FIXTURES[rule][0]))
    assert main([str(bad), "--no-program", "--no-baseline"]) == 1
