"""ISSUE 6 decode-hot-path contracts: donation/aliasing as an asserted
invariant, the kv=paged|dense serving flag, int4 quantized KV, the fused
paged flash-decode kernel, and quantization-aware byte accounting.

The perf claims live in benchmarks/decode_mbu_probe.py and STUDIES §11;
this module pins the CORRECTNESS surface those claims stand on:

  * every donated leaf of every decode-step program (dense f32/int8/
    int4, bucketed, paged, speculative) aliases an output, and the
    StableHLO carries zero cache-sized copies — the static form of
    "the KV update is in-place", enforced here AND by the analysis gate
    (analysis/program.audit_serving_decode);
  * paged-vs-dense token parity under the batcher, through the kv flag's
    three spellings and the auto-sizing path;
  * int4 cache parity across layouts (dense == bucketed == paged — one
    quantizer, three storages) and bounded rounding error vs f32;
  * the paged decode kernel's interpret-mode parity against the
    gather_view einsum oracle;
  * logical_nbytes / kv_bytes_per_pos pricing int4 at its packed half
    byte plus scale rows (the obs/mem + flops satellite).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher


@pytest.fixture(scope="module")
def tiny():
    cfg = gpt.GPTConfig(vocab_size=89, block_size=128, n_layer=2,
                        n_head=2, n_embd=32)
    prepared = gpt.prepare_stacked(
        gpt.init(jax.random.PRNGKey(0), cfg), cfg)
    return cfg, prepared


def _run(cfg, prepared, prompt, new_tokens=16, **kw):
    b = ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=16, **kw)
    rid = b.submit(prompt, max_new_tokens=new_tokens)
    out = b.drain()
    return np.asarray(out[rid]), b


# ----------------------------------------------------------------------
# donation coverage + zero cache-sized copies (the tentpole invariant)
# ----------------------------------------------------------------------

def test_serving_decode_fully_aliased_no_cache_copies():
    from dnn_tpu.analysis.program import audit_serving_decode

    report = audit_serving_decode()
    assert not report["findings"], [f.message for f in report["findings"]]
    # >= : ISSUE 12 added the mixed-step/fused-finish variants, pinned
    # by name in tests/test_overlap.py — this gate only requires that
    # none of the original six ever drop out of the audit
    assert set(report["variants"]) >= {
        "dense_f32", "dense_int8", "dense_int4", "bucketed", "paged",
        "speculative"}
    for name, v in report["variants"].items():
        assert v["aliased"] == v["expected"], (name, v)
        assert v["cache_sized_ops"] == {}, (name, v)


# ----------------------------------------------------------------------
# the kv flag
# ----------------------------------------------------------------------

def test_kv_paged_dense_auto_token_parity(tiny):
    cfg, prepared = tiny
    prompt = np.arange(1, 13) % 89
    t_dense, bd = _run(cfg, prepared, prompt, kv="dense")
    t_paged, bp = _run(cfg, prepared, prompt, kv="paged")
    t_auto, ba = _run(cfg, prepared, prompt, kv="auto")
    assert not bd._paged and bp._paged and ba._paged
    np.testing.assert_array_equal(t_dense, t_paged)
    np.testing.assert_array_equal(t_dense, t_auto)
    # auto-sizing preserves the dense pool's capacity (+ junk block 0)
    assert bp._allocator.n_blocks == 2 * (64 // 16) + 1


def test_kv_auto_falls_back_dense_visibly(tiny):
    cfg, prepared = tiny
    prompt = np.arange(1, 13) % 89
    t_dense, _ = _run(cfg, prepared, prompt, kv="dense")
    # decode_buckets is a dense-pool feature: auto must fall back AND say so
    t_b, bb = _run(cfg, prepared, prompt, kv="auto", decode_buckets=True)
    assert not bb._paged and bb._buckets is not None
    np.testing.assert_array_equal(t_dense, t_b)
    # indivisible geometry falls back too
    b2 = ContinuousBatcher(cfg, prepared, slots=2, max_len=60,
                           prompt_pad=20, kv="auto")
    assert not b2._paged


def test_kv_flag_validation(tiny):
    cfg, prepared = tiny
    with pytest.raises(ValueError, match="paged.*dense|dense.*paged"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64, kv="bogus")
    with pytest.raises(ValueError, match="contradicts"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=16, kv="dense", paged_blocks=8)
    with pytest.raises(ValueError, match="not available"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=16, kv="paged", decode_buckets=True)
    # auto must NOT silently discard an EXPLICIT pool sizing: the same
    # misconfiguration that failed loud pre-flag still fails loud
    with pytest.raises(ValueError, match="not available"):
        ContinuousBatcher(cfg, prepared, slots=2, max_len=64,
                          prompt_pad=16, kv="auto", paged_blocks=8,
                          decode_buckets=True)


# ----------------------------------------------------------------------
# int4 KV
# ----------------------------------------------------------------------

def test_int4_same_tokens_across_layouts(tiny):
    """One quantizer, three storages: dense, bucketed and paged int4
    caches must emit IDENTICAL tokens (each stores the same quantized
    rows; attention math is the shared scaled einsum)."""
    cfg, prepared = tiny
    prompt = (np.arange(1, 19) * 5) % 89
    t_dense, _ = _run(cfg, prepared, prompt, new_tokens=40,
                      kv_dtype="int4")
    t_buck, _ = _run(cfg, prepared, prompt, new_tokens=40,
                     kv_dtype="int4", decode_buckets=True)
    t_paged, _ = _run(cfg, prepared, prompt, new_tokens=40,
                      kv_dtype="int4", kv="paged")
    np.testing.assert_array_equal(t_dense, t_buck)
    np.testing.assert_array_equal(t_dense, t_paged)


def test_int4_attend_close_to_float():
    """Per-row int4 rounding stays bounded: cosine similarity of the
    attended output vs the f32 codec on the same K/V > 0.99."""
    from dnn_tpu.runtime.kvcache import FloatKV, Int4KV

    cfg = gpt.GPTConfig(vocab_size=31, block_size=64, n_layer=1,
                        n_head=2, n_embd=32)
    key = jax.random.PRNGKey(1)
    f32 = FloatKV()
    i4 = Int4KV()
    cf = jax.tree.map(lambda x: x[0], f32.init(cfg, 2, 48))
    ci = jax.tree.map(lambda x: x[0], i4.init(cfg, 2, 48))
    k = jax.random.normal(jax.random.fold_in(key, 1), (2, 2, 40, 16))
    v = jax.random.normal(jax.random.fold_in(key, 2), (2, 2, 40, 16))
    cf = f32.write(cf, k, v, 0)
    ci = i4.write(ci, k, v, 0)
    assert ci["k"].dtype == jnp.int4
    q = jax.random.normal(jax.random.fold_in(key, 3), (2, 2, 1, 16))
    pos = jnp.asarray([20, 39], jnp.int32)
    of = np.asarray(f32.attend_rows(q, cf, pos)).reshape(-1)
    oi = np.asarray(i4.attend_rows(q, ci, pos)).reshape(-1)
    cos = float(np.dot(of, oi)
                / (np.linalg.norm(of) * np.linalg.norm(oi)))
    assert cos > 0.99, cos


def test_int4_rolling_rejected():
    from dnn_tpu.runtime.kvcache import Int4KV, codec_for_cache

    cfg = gpt.GPTConfig(vocab_size=31, block_size=64, n_layer=1,
                        n_head=2, n_embd=32)
    cache = Int4KV().init(cfg, 1, 16)
    with pytest.raises(ValueError, match="rolling int4"):
        codec_for_cache(cache, rolling=True, window=16)


# ----------------------------------------------------------------------
# fused paged flash-decode kernel (interpret mode runs the real index
# maps on CPU)
# ----------------------------------------------------------------------

def test_paged_kernel_matches_gather_einsum():
    from dnn_tpu.ops.pallas.cached_attention import (
        paged_decode_attention,
        reference_paged_decode_attention,
    )

    key = jax.random.PRNGKey(2)
    B, Hk, D, nb, bp, NB = 3, 2, 16, 4, 16, 12
    tables = jnp.asarray(
        np.random.RandomState(0).randint(1, NB, (B, nb)), jnp.int32)
    pos = jnp.asarray([5, 33, 63], jnp.int32)
    for r, quant in ((1, False), (4, False), (1, True)):
        q = jax.random.normal(jax.random.fold_in(key, r), (B, Hk, r, D))
        if quant:
            kp = jax.random.randint(
                jax.random.fold_in(key, 10), (NB, Hk, bp, D), -127, 128,
                dtype=jnp.int32).astype(jnp.int8)
            vp = jax.random.randint(
                jax.random.fold_in(key, 11), (NB, Hk, bp, D), -127, 128,
                dtype=jnp.int32).astype(jnp.int8)
            ks = jax.random.uniform(
                jax.random.fold_in(key, 12), (NB, Hk, bp)) + 0.5
            vs = jax.random.uniform(
                jax.random.fold_in(key, 13), (NB, Hk, bp)) + 0.5
        else:
            kp = jax.random.normal(
                jax.random.fold_in(key, 14), (NB, Hk, bp, D))
            vp = jax.random.normal(
                jax.random.fold_in(key, 15), (NB, Hk, bp, D))
            ks = vs = None
        ref = reference_paged_decode_attention(q, kp, vp, tables, pos,
                                               ks=ks, vs=vs)
        out = paged_decode_attention(q, kp, vp, tables, pos, ks=ks,
                                     vs=vs, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_paged_kernel_serving_parity(tiny):
    """attn_kernel="interpret" on a paged pool runs the REAL kernel
    inside the decode loop — token-identical to the einsum pool."""
    from dnn_tpu.runtime.serving import GPTFamilyRows

    cfg, prepared = tiny
    prompt = np.arange(1, 13) % 89
    t_ein, _ = _run(cfg, prepared, prompt, kv="paged")
    fam = GPTFamilyRows(cfg, attn_kernel="interpret")
    t_ker, bk = _run(cfg, prepared, prompt, kv="paged", family=fam)
    assert bk._paged
    np.testing.assert_array_equal(t_ein, t_ker)


# ----------------------------------------------------------------------
# multi-row gated writes (the gate-folded single-scatter form)
# ----------------------------------------------------------------------

def test_rows_write_multirow_gate_keeps_inactive_rows():
    """A gated-off slot's cache must be untouched by a T>1 verify-shaped
    write (the speculative path) — the gate folds into the written rows,
    not a cache-sized select, and must not smear row 0 over T
    positions."""
    from dnn_tpu.runtime.kvcache import FloatKV

    cfg = gpt.GPTConfig(vocab_size=31, block_size=64, n_layer=1,
                        n_head=2, n_embd=32)
    codec = FloatKV()
    c = jax.tree.map(lambda x: x[0], codec.init(cfg, 2, 32))
    base_k = jax.random.normal(jax.random.PRNGKey(3), c["k"].shape)
    c = {"k": base_k, "v": base_k + 1}
    k_new = jnp.ones((2, 2, 3, 16))
    pos = jnp.asarray([4, 9], jnp.int32)
    gate = jnp.asarray([True, False])
    out = codec.write_rows(c, k_new, k_new, pos, gate)
    # active slot: rows 4..6 overwritten
    np.testing.assert_array_equal(np.asarray(out["k"][0, :, 4:7]), 1.0)
    # inactive slot: bitwise untouched everywhere
    np.testing.assert_array_equal(np.asarray(out["k"][1]),
                                  np.asarray(base_k[1]))


def test_unroll_layers_token_parity(tiny):
    cfg, prepared = tiny
    prompt = np.arange(1, 13) % 89
    t_scan, _ = _run(cfg, prepared, prompt)
    t_unroll, _ = _run(cfg, prepared, prompt, unroll_layers=True)
    np.testing.assert_array_equal(t_scan, t_unroll)


# ----------------------------------------------------------------------
# quantization-aware byte accounting (obs/mem + utils/flops satellite)
# ----------------------------------------------------------------------

def test_logical_nbytes_prices_packed_int4():
    from dnn_tpu.obs.mem import logical_nbytes

    f32 = {"k": jnp.zeros((4, 8), jnp.float32)}
    i8 = {"k": jnp.zeros((4, 8), jnp.int8)}
    i4 = {"k": jnp.zeros((4, 8), jnp.int4)}
    assert logical_nbytes(f32) == 128.0
    assert logical_nbytes(i8) == 32.0
    assert logical_nbytes(i4) == 16.0  # packed half byte, NOT itemsize


def test_kv_bytes_per_pos_quantized_exact():
    from dnn_tpu.utils.flops import kv_bytes_per_pos

    cfg = gpt.GPTConfig(vocab_size=31, block_size=64, n_layer=3,
                        n_head=4, n_embd=64)
    # f32 dtype: 2 leaves x L x C x 4 bytes
    assert kv_bytes_per_pos(cfg, kv_dtype=jnp.float32) == 2 * 3 * 64 * 4
    # int8: 1-byte payload + per-(position, head) f32 K and V scales
    assert kv_bytes_per_pos(cfg, kv_dtype="int8") == \
        2 * 3 * (64 * 1 + 4 * 4)
    # int4: packed half-byte payload + the same scale rows
    assert kv_bytes_per_pos(cfg, kv_dtype="int4") == \
        2 * 3 * (64 * 0.5 + 4 * 4)
    # legacy kv_bytes path unchanged
    assert kv_bytes_per_pos(cfg, kv_bytes=2) == 2 * 3 * 64 * 2


def test_kv_cache_bytes_gauge_tracks_quantization(tiny):
    cfg, prepared = tiny
    _, bf = _run(cfg, prepared, np.arange(1, 5), new_tokens=2)
    _, b4 = _run(cfg, prepared, np.arange(1, 5), new_tokens=2,
                 kv_dtype="int4")
    f32_bytes = bf._kv_bytes_read()
    i4_bytes = b4._kv_bytes_read()
    assert f32_bytes > 0
    # int4 payload is 1/8 of f32; scales push the total a bit above that
    assert i4_bytes < f32_bytes / 4
    assert "serving.kv_cache_bytes" in bf._obs_gauges
