"""PipelineEngine + node CLI: config-to-prediction end to end, checkpoint
loading via every format, runtime selection."""

import json

import numpy as np
import pytest

import jax

from dnn_tpu.config import TopologyConfig
from dnn_tpu.io import checkpoint as ckpt
from dnn_tpu.runtime.engine import PipelineEngine


def _cfg_dict(num_parts=2, **kw):
    d = {
        "nodes": [{"id": f"node{i+1}", "part_index": i} for i in range(num_parts)],
        "num_parts": num_parts,
        "model": "cifar_cnn",
    }
    d.update(kw)
    return d


def test_engine_runtime_auto_spmd():
    eng = PipelineEngine(TopologyConfig.from_dict(_cfg_dict(2)))
    assert eng.runtime == "spmd"  # 8 virtual devices available
    x = eng.spec.example_input(batch_size=2)
    np.testing.assert_allclose(
        np.asarray(eng.run(x)),
        np.asarray(eng.spec.apply(eng.params, x)),
        atol=1e-5, rtol=1e-5,
    )


def test_engine_relay_runtime_matches():
    eng = PipelineEngine(TopologyConfig.from_dict(_cfg_dict(2, runtime="relay")))
    assert eng.runtime == "relay"
    x = eng.spec.example_input(batch_size=2)
    np.testing.assert_allclose(
        np.asarray(eng.run(x)),
        np.asarray(eng.spec.apply(eng.params, x)),
        atol=1e-6,
    )


def test_engine_native_checkpoint_roundtrip(tmp_path):
    """Save our params in the native flat .npz layout, reload via config."""
    eng = PipelineEngine(TopologyConfig.from_dict(_cfg_dict(2)))
    path = tmp_path / "weights.npz"
    ckpt.save_npz(str(path), ckpt.params_to_flat(eng.params))

    eng2 = PipelineEngine(
        TopologyConfig.from_dict(_cfg_dict(2, model_weights=str(path)))
    )
    x = eng.spec.example_input(batch_size=1)
    np.testing.assert_array_equal(np.asarray(eng.run(x)), np.asarray(eng2.run(x)))


def test_engine_torch_checkpoint(tmp_path):
    """The reference's exact deployment artifact: a torch .pth full state
    dict, loaded and sliced per stage (node.py:294-317)."""
    torch = pytest.importorskip("torch")
    import torch.nn as nn

    m = nn.Sequential()
    m.add_module("conv1", nn.Conv2d(3, 32, 3, 1, 1))
    m.add_module("conv2", nn.Conv2d(32, 64, 3, 1, 1))
    m.add_module("fc1", nn.Linear(4096, 512))
    m.add_module("fc2", nn.Linear(512, 10))
    path = tmp_path / "cifar10_model.pth"
    torch.save(m.state_dict(), str(path))

    eng = PipelineEngine(
        TopologyConfig.from_dict(_cfg_dict(2, model_weights=str(path)))
    )
    x = eng.spec.example_input(batch_size=1)
    y = eng.run(x)
    assert y.shape == (1, 10)
    assert eng.predict(x) == int(np.argmax(np.asarray(y)))


def test_engine_rejects_unsupported_parts():
    with pytest.raises(ValueError, match="supports num_parts"):
        PipelineEngine(TopologyConfig.from_dict(_cfg_dict(7)))


def test_engine_gpt_model():
    cfg = TopologyConfig.from_dict(
        {
            "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
            "num_parts": 4,
            "model": "gpt2-test",
            "microbatches": 2,
        }
    )
    eng = PipelineEngine(cfg)
    ids = eng.spec.example_input(batch_size=2, seq_len=16)
    logits = eng.run(ids)
    assert logits.shape == (2, 16, eng.spec.config.vocab_size)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(eng.spec.apply(eng.params, ids)),
        atol=1e-4, rtol=1e-4,
    )


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def test_cli_single_controller(tmp_path, capsys):
    from dnn_tpu.node import main

    cfg = _cfg_dict(2)
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))

    rc = main(["--node_id", "node1", "--config", str(cfg_path),
               "--input_image", "/nonexistent.png"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "FINAL PREDICTION (Index):" in out  # node.py:192 parity


def test_cli_bad_config(tmp_path):
    from dnn_tpu.node import main

    assert main(["--node_id", "x", "--config", str(tmp_path / "missing.json")]) == 1
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_cfg_dict(2)))
    assert main(["--node_id", "ghost", "--config", str(bad)]) == 1


def test_cli_generate_mode(tmp_path, capsys):
    from dnn_tpu.node import main

    cfg = {
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
        "num_parts": 4,
        "model": "gpt2-test",
        "device_type": "cpu",
        "runtime": "spmd",
    }
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))

    rc = main(["--node_id", "n0", "--config", str(cfg_path),
               "--generate", "5", "--prompt_ids", "1,2,3"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "GENERATED TOKENS:" in out
    toks = [int(t) for t in out.split("GENERATED TOKENS:")[1].split("*")[0].strip().split(",")]
    assert len(toks) == 5

    # malformed prompt ids fail cleanly, reference-style exit(1)
    assert main(["--node_id", "n0", "--config", str(cfg_path),
                 "--generate", "3", "--prompt_ids", "a,b"]) == 1

    # CIFAR family has no decode path -> clean error
    cfg2 = _cfg_dict(2)
    cfg2_path = tmp_path / "cifar.json"
    cfg2_path.write_text(json.dumps(cfg2))
    assert main(["--node_id", "node1", "--config", str(cfg2_path),
                 "--generate", "3"]) == 1


def test_engine_stage_role_minimal():
    """role='stage' must work with fewer devices than stages (the --serve
    deployment from a 1-device host) and refuse full-pipeline runs."""
    cfg = TopologyConfig.from_dict(
        {
            "nodes": [{"id": f"n{i}", "part_index": i} for i in range(4)],
            "num_parts": 4,
            "model": "gpt2-test",
            "runtime": "spmd",
        }
    )
    eng = PipelineEngine(cfg, devices=jax.devices()[:1], role="stage")
    assert eng.runtime == "stage"
    ids = eng.spec.example_input(batch_size=1, seq_len=8)
    h = eng.run_stage(0, ids)
    assert h.shape == (1, 8, eng.spec.config.n_embd)
    with pytest.raises(RuntimeError, match="role='stage'"):
        eng.run(ids)


def test_engine_gpt_stacked_fast_path():
    """num_parts dividing n_layer triggers the stacked pipeline (per-stage
    HBM weights); output must still match the full model."""
    cfg = TopologyConfig.from_dict(
        {
            "nodes": [{"id": f"n{i}", "part_index": i} for i in range(2)],
            "num_parts": 2,
            "model": "gpt2-test",
            "microbatches": 2,
        }
    )
    eng = PipelineEngine(cfg)
    assert eng.runtime == "spmd" and eng._gpt_stacked_ready()
    ids = eng.spec.example_input(batch_size=2, seq_len=16)
    np.testing.assert_allclose(
        np.asarray(eng.run(ids)),
        np.asarray(eng.spec.apply(eng.params, ids)),
        atol=1e-4, rtol=1e-4,
    )


def test_engine_bf16_dtype_consumed():
    """config dtype=bfloat16 must actually engage bf16 compute for GPT."""
    base = {
        "nodes": [{"id": f"n{i}", "part_index": i} for i in range(2)],
        "num_parts": 2,
        "model": "gpt2-test",
    }
    eng32 = PipelineEngine(TopologyConfig.from_dict(base))
    eng16 = PipelineEngine(
        TopologyConfig.from_dict({**base, "dtype": "bfloat16"}), params=eng32.params
    )
    assert eng16.compute_dtype is not None
    ids = eng32.spec.example_input(batch_size=2, seq_len=16)
    a, b = np.asarray(eng32.run(ids)), np.asarray(eng16.run(ids))
    diff = np.abs(a - b).max()
    assert 0 < diff < 0.2, f"bf16 diff {diff} (0 means bf16 never engaged)"


def test_engine_compile_once():
    """Repeat calls must reuse the compiled pipeline (no retrace)."""
    cfg = TopologyConfig.from_dict(_cfg_dict(2))
    eng = PipelineEngine(cfg)
    x = eng.spec.example_input(batch_size=2)
    y1 = eng.run(x)
    fn = eng._pipeline_fn
    y2 = eng.run(x)
    assert eng._pipeline_fn is fn
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


def test_cli_beam_mode(tmp_path, capsys):
    """--beam K: deterministic beam decode through the CLI; beam-1-vs-greedy
    parity is covered in tests/test_beam.py, here K>1 must run and print."""
    from dnn_tpu.node import main

    cfg = {
        "nodes": [{"id": "n0", "part_index": 0}],
        "num_parts": 1,
        "model": "gpt2-test",
        "device_type": "cpu",
    }
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))

    rc = main(["--node_id", "n0", "--config", str(cfg_path),
               "--generate", "5", "--prompt_ids", "1,2,3", "--beam", "3"])
    assert rc == 0
    out = capsys.readouterr().out
    toks = [int(t) for t in
            out.split("GENERATED TOKENS:")[1].split("*")[0].strip().split(",")]
    assert len(toks) == 5
    # deterministic: a second identical run prints the same tokens
    main(["--node_id", "n0", "--config", str(cfg_path),
          "--generate", "5", "--prompt_ids", "1,2,3", "--beam", "3"])
    out2 = capsys.readouterr().out
    toks2 = [int(t) for t in
             out2.split("GENERATED TOKENS:")[1].split("*")[0].strip().split(",")]
    assert toks2 == toks

    # CIFAR family -> clean error, reference-style exit(1)
    cfg2 = _cfg_dict(2)
    cfg2_path = tmp_path / "cifar.json"
    cfg2_path.write_text(json.dumps(cfg2))
    assert main(["--node_id", "node1", "--config", str(cfg2_path),
                 "--generate", "3", "--beam", "2"]) == 1


def test_cli_lora_merge(tmp_path, capsys):
    """--lora: the engine merges the adapter artifact at load; trained
    (perturbed) adapters must change the served decode, zero-init (b=0)
    adapters must not."""
    import jax

    from dnn_tpu import lora
    from dnn_tpu.node import main
    from dnn_tpu.registry import get_model

    cfg = {
        "nodes": [{"id": "n0", "part_index": 0}],
        "num_parts": 1,
        "model": "gpt2-test",
        "device_type": "cpu",
    }
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))

    params = get_model("gpt2-test").init(jax.random.PRNGKey(0))
    ad = lora.init_lora(jax.random.PRNGKey(1), params, rank=2)

    def run(lora_path=None):
        argv = ["--node_id", "n0", "--config", str(cfg_path),
                "--generate", "6", "--prompt_ids", "1,2,3"]
        if lora_path:
            argv += ["--lora", lora_path]
        assert main(argv) == 0
        out = capsys.readouterr().out
        return out.split("GENERATED TOKENS:")[1].split("*")[0].strip()

    base = run()
    zero_path = str(tmp_path / "zero.npz")
    lora.save_lora(zero_path, ad)
    assert run(zero_path) == base  # b=0 -> identity merge

    tuned = jax.tree.map(lambda x: x + 0.05, ad)
    tuned_path = str(tmp_path / "tuned.npz")
    lora.save_lora(tuned_path, tuned)
    assert run(tuned_path) != base  # adapters actually change the model


def test_cli_beam_requires_generate(tmp_path):
    """Beam-only flags without --generate must error, not be dropped."""
    from dnn_tpu.node import main

    cfg = {
        "nodes": [{"id": "n0", "part_index": 0}],
        "num_parts": 1,
        "model": "gpt2-test",
        "device_type": "cpu",
    }
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))
    assert main(["--node_id", "n0", "--config", str(cfg_path),
                 "--beam", "4"]) == 1
    assert main(["--node_id", "n0", "--config", str(cfg_path),
                 "--eos_id", "7"]) == 1


def test_cli_generate_mixtral(tmp_path, capsys):
    """The CLI serves Mixtral with zero family-specific wiring: the
    engine's LlamaConfig dispatch catches the subclass and the config
    resolves its own expert hook (default_ffn)."""
    from dnn_tpu.node import main

    cfg = {
        "nodes": [{"id": "n0", "part_index": 0}],
        "num_parts": 1,
        "model": "mixtral-test",
        "device_type": "cpu",
        "runtime": "spmd",
    }
    cfg_path = tmp_path / "mixtral.json"
    cfg_path.write_text(json.dumps(cfg))
    rc = main(["--node_id", "n0", "--config", str(cfg_path),
               "--generate", "4", "--prompt_ids", "5,6,7"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "GENERATED TOKENS:" in out
    toks = [int(t) for t in
            out.split("GENERATED TOKENS:")[1].split("*")[0].strip().split(",")]
    assert len(toks) == 4
