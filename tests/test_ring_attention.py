"""Ring attention (sequence parallelism) vs the dense reference oracle.

The reference has zero long-context support (hard assert T <= block_size,
gpt_model_parts.py:15; SURVEY §5). These tests check the ring produces the
same numbers as full dense attention while only ever holding O(T/n) keys
per device, on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.ops.pallas.flash_attention import reference_attention
from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from dnn_tpu.parallel.ring_attention import ring_attention


def _qkv(b=2, h=3, t=64, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (b, h, t, d), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("n_ring", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(n_ring, causal):
    mesh = make_mesh({SEQ_AXIS: n_ring})
    q, k, v = _qkv()
    got = ring_attention(q, k, v, mesh=mesh, causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_rejects_indivisible_seq():
    mesh = make_mesh({SEQ_AXIS: 4})
    q, k, v = _qkv(t=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh)


def test_ring_under_jit_and_grad():
    mesh = make_mesh({SEQ_AXIS: 4})
    q, k, v = _qkv(t=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), atol=3e-4)
