"""Ring attention (sequence parallelism) vs the dense reference oracle.

The reference has zero long-context support (hard assert T <= block_size,
gpt_model_parts.py:15; SURVEY §5). These tests check the ring produces the
same numbers as full dense attention while only ever holding O(T/n) keys
per device, on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.ops.pallas.flash_attention import reference_attention
from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh
from dnn_tpu.parallel.ring_attention import ring_attention


def _qkv(b=2, h=3, t=64, d=16, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    mk = lambda k: jax.random.normal(k, (b, h, t, d), jnp.float32)
    return mk(ks[0]), mk(ks[1]), mk(ks[2])


@pytest.mark.parametrize("n_ring", [2, 4, 8])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(n_ring, causal):
    mesh = make_mesh({SEQ_AXIS: n_ring})
    q, k, v = _qkv()
    got = ring_attention(q, k, v, mesh=mesh, causal=causal)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def _banded_reference(q, k, v, window):
    """Dense band-masked attention oracle: causal upper bound plus the
    sliding-window lower bound (q_pos - k_pos < window)."""
    d = q.shape[-1]
    s = jnp.einsum("bhtd,bhsd->bhts", q, k).astype(jnp.float32) \
        / jnp.sqrt(d)
    t = q.shape[2]
    delta = jnp.arange(t)[:, None] - jnp.arange(t)[None, :]
    keep = (delta >= 0) & (delta < window)
    s = jnp.where(keep[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhts,bhsd->bhtd", p, v.astype(jnp.float32)) \
        .astype(q.dtype)


@pytest.mark.parametrize("n_ring", [2, 4, 8])
@pytest.mark.parametrize("window", [5, 8, 17, 64])
def test_banded_ring_matches_dense_band(n_ring, window):
    """Sliding-window ring == the dense band-masked oracle — windows
    smaller than a shard (the ring stops after 2 hops), spanning several
    shards, and covering the whole sequence (degenerates to causal)."""
    mesh = make_mesh({SEQ_AXIS: n_ring})
    q, k, v = _qkv()
    got = ring_attention(q, k, v, mesh=mesh, causal=True, window=window)
    want = _banded_reference(q, k, v, window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5)


def test_banded_ring_rejects_non_causal_and_bad_window():
    mesh = make_mesh({SEQ_AXIS: 4})
    q, k, v = _qkv()
    with pytest.raises(ValueError, match="causal"):
        ring_attention(q, k, v, mesh=mesh, causal=False, window=8)
    with pytest.raises(ValueError, match="window"):
        ring_attention(q, k, v, mesh=mesh, causal=True, window=0)


def test_banded_ring_skips_dead_hops():
    """The banded schedule is structural, not just a mask: with
    window <= T_local the ring scans ceil(w/T_local)+1 = 2 blocks
    instead of n — visible as the scan's static trip count in the
    jaxpr (n-2 fewer ppermute pairs per call)."""
    from dnn_tpu.parallel.ring_attention import ring_attention_local

    def count_ppermutes(window):
        mesh = make_mesh({SEQ_AXIS: 8})
        q, k, v = _qkv()
        import functools

        from jax.sharding import PartitionSpec as P

        body = functools.partial(ring_attention_local, causal=True,
                                 window=window)
        spec = P(None, None, SEQ_AXIS, None)
        fn = jax.shard_map(body, mesh=mesh, in_specs=(spec,) * 3,
                           out_specs=spec, check_vma=False)
        text = str(jax.make_jaxpr(fn)(q, k, v))
        import re

        # scan trip count appears as `length=N`; ppermutes inside count
        # once in the jaxpr body regardless of trip count — read the
        # scan length instead
        m = re.search(r"length=(\d+)", text)
        return int(m.group(1)) if m else 0

    assert count_ppermutes(window=None) == 7   # full ring: n-1 hops
    assert count_ppermutes(window=8) == 1      # banded: 2 live blocks
    # block i's min delta is (i-1)*t_kv+1: window=17 at t_kv=8 leaves
    # exactly 3 live blocks (a naive ceil(w/t_kv)+1 would scan a fully
    # -masked 4th) and window=1 needs only the diagonal block
    assert count_ppermutes(window=17) == 2
    assert count_ppermutes(window=1) == 0


def test_ring_rejects_indivisible_seq():
    mesh = make_mesh({SEQ_AXIS: 4})
    q, k, v = _qkv(t=30)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh)


def test_ring_under_jit_and_grad():
    mesh = make_mesh({SEQ_AXIS: 4})
    q, k, v = _qkv(t=32)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, causal=True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    g_dense = jax.grad(loss_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_dense), atol=3e-4)
