"""trainlens tests (ISSUE 19): the training-step observatory.

The acceptance contract this module pins: TrainClock's phase
arithmetic and stall attribution are exact on an injected clock, the
published MFU/tokens-per-sec agree with hand arithmetic a reviewer can
redo, the batched registry flush bills the train.* counters/histograms
and the weak dnn_tpu_train_* gauges, checkpoint freshness
(staleness/last-good-step) follows save/restore through both the clock
and the module-level note_* wires, the GradSentinel's three detectors
(loss_nan latch + incident bundle, grad_spike EMA, train_stall run)
fire exactly once per episode, the obs gate makes every producer a
no-op when off, /trainz serves JSON and Prometheus text, the
`python -m dnn_tpu.obs trainlens` CLI smoke passes — and one real
`train.fit` run on a tiny GPT (grad_stats leg live, periodic
checkpointing, chaos sleep/nan vectors) feeds every seam end to end."""

import json
import math
import os
import subprocess
import sys
import urllib.request

import pytest

from dnn_tpu import obs
from dnn_tpu.obs import flight
from dnn_tpu.obs import trainlens as tl
from dnn_tpu.obs.trainlens import (
    TRAIN_PHASES,
    GradSentinel,
    TrainClock,
    note_ckpt_restored,
    note_ckpt_saved,
)
from dnn_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _obs_on():
    """Producers self-gate; unit legs run with the gate ON and restore."""
    was = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(was)


def _steps(clk, t, n, *, data=0.020, dispatch=0.004, wait=0.016,
           tail=0.010):
    """Drive n deterministic iterations through the producer protocol
    on the injected clock `t` (seconds per phase as given; the tail
    lands in "obs")."""
    for _ in range(n):
        rec = clk.begin()
        assert rec is not None
        for phase, dt in (("data", data), ("dispatch", dispatch),
                          ("wait", wait)):
            t[0] += dt
            clk.mark(rec, phase)
        t[0] += tail
        clk.end(rec)


# ----------------------------------------------------------------------
# phase arithmetic + derived series (injected clock goldens)
# ----------------------------------------------------------------------

def test_phase_arithmetic_golden():
    t = [50.0]
    clk = TrainClock(capacity=16, registry=Metrics(), now=lambda: t[0])
    _steps(clk, t, 4)
    s = clk.summary()
    # per step: wall 50 ms = data 20 + dispatch 4 + wait 16 + obs 10
    assert s["steps_total"] == 4 and s["window_steps"] == 4
    assert s["window_wall_s"] == pytest.approx(4 * 0.050)
    assert s["phases"]["data"]["s"] == pytest.approx(4 * 0.020)
    assert s["phases"]["dispatch"]["mean_ms"] == pytest.approx(4.0)
    assert s["phases"]["wait"]["frac"] == pytest.approx(0.32)
    # the unmarked tail folds into "obs", never into dark time
    assert s["phases"]["obs"]["s"] == pytest.approx(4 * 0.010)
    assert s["phases"]["ckpt"]["s"] == 0.0
    assert s["data_stall_fraction"] == pytest.approx(0.4)
    assert sum(d["s"] for d in s["phases"].values()) == pytest.approx(
        s["window_wall_s"])
    recs = clk.records()
    assert [r["wall"] for r in recs] == pytest.approx([0.050] * 4)
    assert set(recs[0]["phases"]) == {"data", "dispatch", "wait", "obs"}


def test_rate_mfu_and_tokens_agree_with_hand_arithmetic():
    t = [200.0]
    clk = TrainClock(capacity=32, registry=Metrics(),
                     flops_per_step=2e6, tokens_per_step=128,
                     peak_flops=1e9, now=lambda: t[0])
    _steps(clk, t, 5)
    # ring spans first-begin -> now = 5 x 50 ms
    sps = 5 / 0.250
    s = clk.summary()
    assert s["steps_per_sec"] == pytest.approx(sps, rel=1e-3)
    assert s["tokens_per_sec"] == pytest.approx(128 * sps, rel=1e-3)
    assert s["tokens"] == 5 * 128
    assert s["mfu"] == pytest.approx(2e6 * sps / 1e9, abs=1e-6)
    assert clk.mfu() == pytest.approx(0.04, abs=1e-6)
    # explicit per-iteration tokens override the per-step default
    rec = clk.begin()
    t[0] += 0.05
    clk.end(rec, tokens=7)
    assert clk.records()[-1]["tokens"] == 7


def test_mfu_is_none_not_zero_when_unpriced():
    t = [0.0]
    clk = TrainClock(capacity=4, registry=Metrics(), peak_flops=1e12,
                     now=lambda: t[0])
    _steps(clk, t, 2)
    assert clk.mfu() is None            # no flops_per_step
    assert clk.summary()["mfu"] is None
    assert clk._mfu_read() == 0.0       # the gauge reads 0, not None


def test_data_stall_memoized_per_landed_step():
    t = [0.0]
    clk = TrainClock(capacity=16, registry=Metrics(), now=lambda: t[0])
    _steps(clk, t, 2)
    a = clk.data_stall_fraction()
    assert clk.data_stall_fraction() is a or \
        clk.data_stall_fraction() == a  # cached, same key
    _steps(clk, t, 2, data=0.040)       # heavier data phase shifts it
    assert clk.data_stall_fraction() > a


def test_registry_flush_bills_counters_hists_and_gauges():
    t = [0.0]
    reg = Metrics()
    clk = TrainClock(capacity=16, registry=reg, flops_per_step=1e6,
                     tokens_per_step=32, peak_flops=1e9,
                     now=lambda: t[0])
    _steps(clk, t, 3)
    clk.flush()
    snap = reg.snapshot()
    assert snap["counters"]["train.steps_total"] == 3
    assert snap["counters"]["train.tokens_total"] == 96
    assert 'train.phase_seconds{phase="data"}' in snap["histogram"]
    assert snap["histogram"]["train.wall_seconds"]["count"] == 3
    # the weak gauges landed as FULL prom family names (the fleet
    # rollup reads them off /metrics text verbatim)
    for fam in ("dnn_tpu_train_mfu", "dnn_tpu_train_data_stall",
                "dnn_tpu_train_tokens_per_sec",
                "dnn_tpu_ckpt_staleness_seconds"):
        assert fam in snap["gauges"], fam


def test_render_prom_and_chrome_trace():
    t = [10.0]
    clk = TrainClock(capacity=8, registry=Metrics(), flops_per_step=1e6,
                     peak_flops=1e9, now=lambda: t[0])
    _steps(clk, t, 3)
    prom = clk.render_prom()
    assert "dnn_tpu_train_steps_total 3" in prom
    assert 'dnn_tpu_train_phase_frac{phase="data"}' in prom
    ct = clk.chrome_trace()
    xs = [e for e in ct["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 3 * 3  # one slice per marked phase per step
    assert xs[0]["ts"] == 0.0  # rebased to the oldest record


def test_ring_capacity_bounds_the_window():
    t = [0.0]
    clk = TrainClock(capacity=4, registry=Metrics(), now=lambda: t[0])
    _steps(clk, t, 10)
    s = clk.summary()
    assert s["steps_total"] == 10 and s["window_steps"] == 4


def test_gate_off_records_nothing():
    obs.set_enabled(False)
    t = [0.0]
    clk = TrainClock(capacity=4, registry=Metrics(), now=lambda: t[0])
    assert clk.begin() is None
    assert clk.steps_total == 0 and clk.records() == []
    sen = GradSentinel()
    assert sen.observe(1, float("nan")) == []
    assert sen.events_fired == 0


# ----------------------------------------------------------------------
# checkpoint observability
# ----------------------------------------------------------------------

def test_ckpt_freshness_arithmetic():
    t = [1000.0]
    reg = Metrics()
    clk = TrainClock(capacity=4, registry=reg, now=lambda: t[0])
    # no save yet: "nothing to lose", not an alarm
    assert clk.ckpt_staleness_s() == 0.0
    clk.ckpt_saved(10, 0.5, 2e6)
    t[0] += 3.0
    assert clk.ckpt_staleness_s() == pytest.approx(3.0)
    assert clk.summary()["ckpt"]["last_good_step"] == 10
    # a restore is also a known-good point: staleness resets
    clk.ckpt_restored(7, 0.2, 2e6)
    assert clk.ckpt_staleness_s() == pytest.approx(0.0)
    assert clk.summary()["ckpt"]["last_good_step"] == 7
    snap = reg.snapshot()
    assert snap["counters"]["train.ckpt_saves"] == 1
    assert snap["counters"]["train.ckpt_restores"] == 1
    assert snap["histogram"]["train.ckpt_save_seconds"]["count"] == 1
    assert snap["histogram"]["train.ckpt_restore_bytes"]["count"] == 1


def test_note_ckpt_wires_flight_and_active_clock():
    t = [0.0]
    clk = TrainClock(capacity=4, registry=Metrics(),
                     now=lambda: t[0]).install()
    assert tl.active_trainlens() is clk
    before = len(flight.recorder().events(kind="ckpt_saved"))
    note_ckpt_saved(5, 0.125, 4096)
    evs = flight.recorder().events(kind="ckpt_saved")
    assert len(evs) == before + 1
    assert evs[-1]["step"] == 5 and evs[-1]["bytes"] == 4096
    assert clk.summary()["ckpt"]["last_good_step"] == 5
    note_ckpt_restored(5, 0.06, 4096)
    assert flight.recorder().events(kind="ckpt_restored")
    # gate off: the helpers are one boolean check, no event, no clock
    obs.set_enabled(False)
    note_ckpt_saved(9, 0.1, 1)
    obs.set_enabled(True)
    assert clk.summary()["ckpt"]["last_good_step"] == 5


# ----------------------------------------------------------------------
# gradient-health sentinels
# ----------------------------------------------------------------------

def test_sentinel_constructor_validation():
    with pytest.raises(ValueError):
        GradSentinel(spike_factor=1.0)
    with pytest.raises(ValueError):
        GradSentinel(ema_alpha=0.0)


def test_sentinel_nan_latches_once_per_episode():
    sen = GradSentinel(warmup=1)
    assert sen.observe(1, 1.0, [1.0, 0.01, 0]) == []
    assert sen.observe(2, float("nan")) == ["loss_nan"]
    assert sen.observe(3, float("nan")) == []        # latched
    assert sen.observe(4, 0.9) == []                 # recovers
    assert sen.observe(5, float("inf")) == ["loss_nan"]  # new episode
    # nonfinite GRADS alone (finite loss) also count as divergence
    sen2 = GradSentinel(warmup=1)
    assert sen2.observe(1, 0.5, [1.0, 0.01, 2]) == ["loss_nan"]
    assert sen.events_fired == 2 and sen2.events_fired == 1


def test_sentinel_spike_ema_and_warmup():
    sen = GradSentinel(warmup=3, spike_factor=4.0, ema_alpha=0.5)
    # a huge norm INSIDE warmup must not fire (it seeds the EMA)
    assert sen.observe(1, 1.0, [1.0, 0.01, 0]) == []
    assert sen.observe(2, 1.0, [100.0, 0.01, 0]) == []
    for i in range(3, 6):
        assert sen.observe(i, 1.0, [1.0, 0.01, 0]) == []
    ema = sen._ema
    assert sen.observe(6, 1.0, [ema * 5, 0.01, 0]) == ["grad_spike"]
    assert sen.observe(7, 1.0, [ema * 9, 0.01, 0]) == []  # latched
    assert sen.observe(8, 1.0, [1.0, 0.01, 0]) == []      # unlatch
    # a NaN norm must not poison the EMA baseline
    base = sen._ema
    sen.observe(9, 1.0, [float("nan"), 0.01, 0])
    assert sen._ema == base


def test_sentinel_stall_needs_consecutive_run():
    sen = GradSentinel(warmup=1, stall_ratio=1e-6, stall_steps=3)
    assert sen.observe(1, 1.0, [1.0, 0.0, 0]) == []
    assert sen.observe(2, 1.0, [1.0, 0.0, 0]) == []
    # movement resets the run
    assert sen.observe(3, 1.0, [1.0, 0.5, 0]) == []
    assert sen.observe(4, 1.0, [1.0, 0.0, 0]) == []
    assert sen.observe(5, 1.0, [1.0, 0.0, 0]) == []
    assert sen.observe(6, 1.0, [1.0, 0.0, 0]) == ["train_stall"]
    assert sen.observe(7, 1.0, [1.0, 0.0, 0]) == []  # latched


def test_sentinel_nan_writes_incident_bundle(tmp_path):
    bundle = tmp_path / "incident"
    clk = TrainClock(capacity=4, registry=Metrics(),
                     now=lambda: 0.0).install()
    sen = GradSentinel(warmup=1, bundle_dir=str(bundle), clock=clk)
    assert sen.observe(3, float("nan"), [1.0, 0.01, 1]) == ["loss_nan"]
    assert bundle.is_dir() and any(bundle.iterdir())
    evs = flight.recorder().events(kind="loss_nan")
    assert evs and evs[-1]["step"] == 3
    assert evs[-1]["nonfinite_grads"] == 1
    assert math.isnan(evs[-1]["loss"])


# ----------------------------------------------------------------------
# /trainz endpoint + CLI
# ----------------------------------------------------------------------

def test_trainz_endpoint_json_and_prom():
    t = [0.0]
    clk = TrainClock(capacity=8, registry=Metrics(), flops_per_step=1e6,
                     tokens_per_step=64, peak_flops=1e9,
                     now=lambda: t[0])
    _steps(clk, t, 4)
    srv = obs.serve_metrics(0, trainlens=clk)
    try:
        base = f"http://127.0.0.1:{srv.port}/trainz"
        z = json.loads(urllib.request.urlopen(
            base, timeout=10).read().decode())
        assert z["steps_total"] == 4
        assert set(z["phases"]) == set(TRAIN_PHASES)
        assert z["data_stall_fraction"] == pytest.approx(0.4)
        prom = urllib.request.urlopen(
            base + "?format=prom", timeout=10).read().decode()
        assert "dnn_tpu_train_mfu" in prom
        assert "dnn_tpu_ckpt_staleness_seconds" in prom
    finally:
        srv.close()


def test_cli_selftest_and_saved_dump(tmp_path):
    r = subprocess.run([sys.executable, "-m", "dnn_tpu.obs", "trainlens",
                        "--selftest"], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "trainlens selftest ok" in r.stdout
    # the offline render path: a saved `curl .../trainz` dump
    t = [0.0]
    clk = TrainClock(capacity=8, registry=Metrics(), now=lambda: t[0])
    _steps(clk, t, 2)
    path = tmp_path / "trainz.json"
    path.write_text(json.dumps(clk.summary()))
    r = subprocess.run([sys.executable, "-m", "dnn_tpu.obs", "trainlens",
                        str(path)], capture_output=True, text=True,
                       timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "data stall" in r.stdout


# ----------------------------------------------------------------------
# real fit() e2e: every seam fed by the actual training loop
# ----------------------------------------------------------------------

def _toy_linear():
    """A FLOAT toy model (the chaos nan vector poisons float leaves
    only — token batches are int on purpose) with the grad_stats leg."""
    import jax
    import jax.numpy as jnp
    import optax

    from dnn_tpu.train import make_train_step

    params = {"w": jax.random.normal(jax.random.PRNGKey(0), (8,)),
              "b": jnp.zeros(())}
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
    y = x @ jax.random.normal(jax.random.PRNGKey(2), (8,))

    def loss_fn(p, batch):
        pred = batch["x"] @ p["w"] + p["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.sgd(1e-2)
    raw = make_train_step(loss_fn, opt, grad_stats=True)

    def step_fn(state, batch):
        p, s = state
        p, s, loss, stats = raw(p, s, batch)
        return (p, s), loss, stats

    def batches():
        while True:
            yield {"x": x, "y": y}

    return step_fn, (params, opt.init(params)), batches


def test_fit_e2e_feeds_clock_ckpt_and_flight(tmp_path):
    from dnn_tpu.train import fit, resume_or_init

    step_fn, state, batches = _toy_linear()
    clk = TrainClock(capacity=32, flops_per_step=1e3, tokens_per_step=16,
                     peak_flops=1e12, registry=Metrics()).install()
    sen = GradSentinel(warmup=2)
    first_before = len(flight.recorder().events(kind="train_step"))
    out_state, loss = fit(step_fn, state, batches(), num_steps=6,
                          ckpt_dir=str(tmp_path), ckpt_every=3,
                          clock=clk, sentinel=sen)
    assert loss is not None and math.isfinite(float(loss))
    s = clk.summary()
    assert s["steps_total"] == 6 and s["window_steps"] == 6
    # every phase boundary was marked — including the ckpt/eval slots
    assert set(clk.records()[0]["phases"]) >= {"data", "dispatch",
                                               "wait", "ckpt", "eval"}
    # two periodic saves landed in the freshness gauges + flight ring
    assert s["ckpt"]["last_good_step"] == 6
    saves = [e for e in flight.recorder().events(kind="ckpt_saved")
             if e["step"] in (3, 6)]
    assert len(saves) == 2 and all(e["bytes"] > 0 for e in saves)
    steps_ev = flight.recorder().events(kind="train_step")
    assert len(steps_ev) > first_before  # first-step + checkpointed
    assert sen.events_fired == 0  # a healthy run fires nothing
    # the resume path: restore-latest-good notes ckpt_restored
    restored, start = resume_or_init(str(tmp_path), state)
    assert start == 6
    assert flight.recorder().events(kind="ckpt_restored")[-1]["step"] == 6


def test_fit_chaos_sleep_lands_in_data_stall():
    from dnn_tpu.chaos import inject as chaos
    from dnn_tpu.train import fit

    step_fn, state, batches = _toy_linear()
    clk = TrainClock(capacity=16, registry=Metrics()).install()
    chaos.install({"seed": 0, "faults": [
        {"kind": "train_fault", "target": "sleep", "at_n": 0,
         "count": 2, "delay_s": 0.05}]})
    try:
        fit(step_fn, state, batches(), num_steps=4, clock=clk)
    finally:
        chaos.uninstall()
    s = clk.summary()
    # the injected 2 x 50 ms sleeps are inside the data window
    assert s["phases"]["data"]["s"] >= 0.09
    assert s["data_stall_fraction"] >= 0.09 / s["window_wall_s"] * 0.9


def test_fit_chaos_nan_fires_sentinel_within_budget(tmp_path):
    from dnn_tpu.chaos import inject as chaos
    from dnn_tpu.train import fit

    step_fn, state, batches = _toy_linear()
    sen = GradSentinel(warmup=1, bundle_dir=str(tmp_path / "inc"))
    before = len(flight.recorder().events(kind="loss_nan"))
    # chaos counter n is 0-indexed: at_n=2 poisons fit step 3
    chaos.install({"seed": 0, "faults": [
        {"kind": "train_fault", "target": "nan", "at_n": 2,
         "count": 1}]})
    try:
        fit(step_fn, state, batches(), num_steps=5, sentinel=sen,
            clock=None)
    finally:
        chaos.uninstall()
    evs = flight.recorder().events(kind="loss_nan")[before:]
    assert evs, "sentinel never fired on the poisoned batch"
    assert evs[-1]["step"] - 3 <= 2  # the probe's SENTINEL_MAX_STEPS
    assert (tmp_path / "inc").is_dir()
