"""Speculative continuous batching (runtime/serving_spec.py).

Parity contract: GREEDY spec-batcher output is token-identical to the
plain continuous batcher — acceptance only changes how many serial steps
it took, never the tokens (the solo speculative module's guarantee,
lifted to per-slot acceptance counts). Sampled mode is seeded-
deterministic and budget-exact; a draft that IS the target accepts
everything."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.serving import ContinuousBatcher
from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

TCFG = gpt.GPTConfig(block_size=128, vocab_size=128, n_layer=3, n_head=4,
                     n_embd=64)
DCFG = gpt.GPTConfig(block_size=128, vocab_size=128, n_layer=1, n_head=2,
                     n_embd=32)


def _prep(cfg, seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), cfg), cfg)


def _prompt(seed, n=8):
    return np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 0, TCFG.vocab_size,
        dtype=jnp.int32))


@pytest.fixture(scope="module")
def models():
    return _prep(TCFG), _prep(DCFG, seed=1)


def test_greedy_spec_matches_plain_batcher(models):
    """Mixed-length pool, staggered arrival: every request's greedy
    tokens equal the plain batcher's."""
    tprep, dprep = models
    reqs = [(_prompt(1, 9), 10), (_prompt(2, 17), 7), (_prompt(3, 6), 12)]

    def run(spec):
        if spec:
            srv = SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=3,
                                     slots=2, max_len=64, prompt_pad=16)
        else:
            srv = ContinuousBatcher(TCFG, tprep, slots=2, max_len=64,
                                    prompt_pad=16)
        r1 = srv.submit(*reqs[0][:1], max_new_tokens=reqs[0][1])
        r2 = srv.submit(reqs[1][0], max_new_tokens=reqs[1][1])
        srv.step()  # staggered: r3 arrives mid-decode once a slot frees
        while srv.free_slots() == 0:
            srv.step()
        r3 = srv.submit(reqs[2][0], max_new_tokens=reqs[2][1])
        out = srv.drain()
        return [out[r] for r in (r1, r2, r3)]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_greedy_spec_matches_plain_bf16(models):
    """Token identity holds under bf16 compute too: the verify block's
    attention mirrors attend_rows' op/dtype recipe exactly."""
    tprep, dprep = models

    def run(spec):
        kw = dict(slots=1, max_len=64, prompt_pad=16,
                  compute_dtype=jnp.bfloat16)
        srv = (SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=3, **kw)
               if spec else ContinuousBatcher(TCFG, tprep, **kw))
        rid = srv.submit(_prompt(15, 9), max_new_tokens=8)
        return srv.drain()[rid]

    np.testing.assert_array_equal(run(True), run(False))


def test_budget_exact_and_reasons(models):
    tprep, dprep = models
    srv = SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=4, slots=2,
                             max_len=64, prompt_pad=16)
    rid = srv.submit(_prompt(4, 8), max_new_tokens=6)
    out = srv.drain()
    assert len(out[rid]) == 6  # mid-chunk overshoot discarded
    assert srv.finish_reasons[rid] == "length"


def test_stop_sequence_mid_chunk(models):
    """A stop hit inside a committed chunk retires the slot and trims
    exactly as the plain batcher does."""
    tprep, dprep = models
    plain = ContinuousBatcher(TCFG, tprep, slots=1, max_len=64,
                              prompt_pad=16)
    rid0 = plain.submit(_prompt(5, 8), max_new_tokens=8)
    full = plain.drain()[rid0]
    stop = full[2:4]
    first_end = next(i for i in range(1, len(full))
                     if (full[i - 1:i + 1] == stop).all())

    srv = SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=4, slots=1,
                             max_len=64, prompt_pad=16)
    rid = srv.submit(_prompt(5, 8), max_new_tokens=8, stop=[stop])
    got = srv.drain()[rid]
    np.testing.assert_array_equal(got, full[:first_end - 1])
    assert srv.finish_reasons[rid] == "stop"


def test_self_draft_accepts_everything(models):
    """Draft == target: every proposal matches, acceptance rate is 1 and
    each step commits k+1 tokens."""
    tprep, _ = models
    srv = SpeculativeBatcher(TCFG, tprep, TCFG, tprep, spec_k=3, slots=1,
                             max_len=64, prompt_pad=16)
    rid = srv.submit(_prompt(6, 8), max_new_tokens=12)
    out = srv.drain()
    assert len(out[rid]) == 12
    assert srv.spec_accepted == srv.spec_proposed  # all accepted
    # 11 post-prefill tokens in ceil(11/4) = 3 steps
    assert srv.spec_steps == 3


def test_sampled_seeded_deterministic(models):
    tprep, dprep = models
    def run():
        srv = SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=3,
                                 slots=2, max_len=64, prompt_pad=16,
                                 temperature=0.9, top_k=20)
        r1 = srv.submit(_prompt(7, 9), max_new_tokens=8, seed=11)
        r2 = srv.submit(_prompt(8, 7), max_new_tokens=6, seed=12)
        out = srv.drain()
        return out[r1], out[r2]

    a1, a2 = run()
    b1, b2 = run()
    np.testing.assert_array_equal(a1, b1)
    np.testing.assert_array_equal(a2, b2)
    assert len(a1) == 8 and len(a2) == 6
    assert (a1 >= 0).all() and (a1 < TCFG.vocab_size).all()


def test_spec_daemon_matches_dense_daemon(models):
    """The LM daemon with draft_cfg serves through the SpeculativeBatcher:
    greedy unary AND streaming results over gRPC equal the dense daemon's
    (the worker emits each committed token of a multi-token step)."""
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    tprep, dprep = models
    prompt = np.asarray(_prompt(20, 10))

    t1, stop1 = start_lm_server_in_background(
        TCFG, tprep, port=59291, slots=2, max_len=64, prompt_pad=16)
    t2, stop2 = start_lm_server_in_background(
        TCFG, tprep, port=59292, slots=2, max_len=64, prompt_pad=16,
        draft_cfg=DCFG, draft_prepared=dprep, spec_k=3)
    try:
        c1, c2 = NodeClient("127.0.0.1:59291"), NodeClient("127.0.0.1:59292")
        want = c1.generate(prompt, max_new_tokens=8)
        got = c2.generate(prompt, max_new_tokens=8)
        np.testing.assert_array_equal(got, want)
        streamed = list(c2.generate_stream(prompt, max_new_tokens=8))
        np.testing.assert_array_equal(np.asarray(streamed, np.int32), want)
        c1.close()
        c2.close()
    finally:
        stop1()
        stop2()


def test_validation(models):
    tprep, dprep = models
    with pytest.raises(ValueError, match="vocab"):
        bad = gpt.GPTConfig(block_size=64, vocab_size=99, n_layer=1,
                            n_head=2, n_embd=32)
        SpeculativeBatcher(TCFG, tprep, bad, _prep(bad), slots=1,
                           max_len=64)
    with pytest.raises(ValueError, match="int8"):
        SpeculativeBatcher(TCFG, tprep, DCFG, dprep, slots=1, max_len=64,
                           kv_dtype="int8")
    srv = SpeculativeBatcher(TCFG, tprep, DCFG, dprep, spec_k=4, slots=1,
                             max_len=32, prompt_pad=16)
    with pytest.raises(ValueError, match="spec_k"):
        srv.submit(_prompt(9, 3), max_new_tokens=4)   # prompt < k+1
    with pytest.raises(ValueError, match="exceeds max_len"):
        srv.submit(_prompt(9, 16), max_new_tokens=16)  # 16+16+4 > 32
    with pytest.raises(ValueError, match="per-request"):
        srv.submit(_prompt(9, 8), max_new_tokens=4, temperature=0.5)


# ----------------------------------------------------------------------
# family-adapter speculation (LLaMA targets/drafts)
# ----------------------------------------------------------------------

def test_llama_family_speculative_greedy_parity():
    """A LLaMA target + LLaMA draft through the speculative batcher must
    be token-identical to the plain batcher on the same target — GQA
    verify (per-row within-block causality on the KV-width cache) is the
    program under test."""
    from dnn_tpu.models import llama
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = llama.PRESETS["llama-test"]
    d_cfg = dataclasses.replace(cfg, n_layer=1)
    t_params = llama.init(jax.random.PRNGKey(21), cfg)
    d_params = llama.init(jax.random.PRNGKey(22), d_cfg)
    tprep = gpt.prepare_stacked(t_params, cfg)
    dprep = gpt.prepare_stacked(d_params, d_cfg)

    prompts = [np.arange(5, 13) % cfg.vocab_size,
               np.asarray([3, 1, 4, 1, 5, 9, 2, 6])]
    n_new = 9

    plain = ContinuousBatcher(cfg, tprep, slots=2, max_len=64,
                              prompt_pad=8,
                              family=llama.LlamaFamilyRows(cfg))
    want = {i: plain.submit(p, max_new_tokens=n_new)
            for i, p in enumerate(prompts)}
    plain.drain()

    spec = SpeculativeBatcher(
        cfg, tprep, d_cfg, dprep, spec_k=3, slots=2, max_len=64,
        prompt_pad=8, family=llama.LlamaFamilyRows(cfg),
        draft_family=llama.LlamaFamilyRows(d_cfg))
    got = {i: spec.submit(p, max_new_tokens=n_new)
           for i, p in enumerate(prompts)}
    spec.drain()
    for i in want:
        np.testing.assert_array_equal(spec.results[got[i]],
                                      plain.results[want[i]])
    assert spec.spec_accepted >= 0  # telemetry intact


def test_cross_family_gpt_draft_llama_target():
    """Cross-family speculation: a GPT-2 draft proposes for a LLaMA
    target (matching vocabs is the only requirement); greedy output must
    equal the target-only decode."""
    from dnn_tpu.models import llama
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg = llama.PRESETS["llama-test"]
    d_cfg = gpt.PRESETS["gpt2-test"]  # also V=256
    assert d_cfg.vocab_size == cfg.vocab_size
    t_params = llama.init(jax.random.PRNGKey(23), cfg)
    d_params = gpt.init(jax.random.PRNGKey(24), d_cfg)
    tprep = gpt.prepare_stacked(t_params, cfg)
    dprep = gpt.prepare_stacked(d_params, d_cfg)

    prompt = np.asarray([7, 7, 3, 2, 9, 11])
    n_new = 8
    plain = ContinuousBatcher(cfg, tprep, slots=1, max_len=64,
                              prompt_pad=8,
                              family=llama.LlamaFamilyRows(cfg))
    rid_w = plain.submit(prompt, max_new_tokens=n_new)
    plain.drain()

    spec = SpeculativeBatcher(cfg, tprep, d_cfg, dprep, spec_k=2,
                              slots=1, max_len=64, prompt_pad=8,
                              family=llama.LlamaFamilyRows(cfg))
    rid_g = spec.submit(prompt, max_new_tokens=n_new)
    spec.drain()
    np.testing.assert_array_equal(spec.results[rid_g],
                                  plain.results[rid_w])


def test_spec_rejects_windowed_family():
    from dnn_tpu.models import llama

    cfg = llama.PRESETS["mistral-test"]
    params = llama.init(jax.random.PRNGKey(25), cfg)
    prep = gpt.prepare_stacked(params, cfg)
    with pytest.raises(ValueError, match="dense-attention"):
        SpeculativeBatcher(cfg, prep, cfg, prep, spec_k=2, slots=1,
                           max_len=48, prompt_pad=8,
                           family=llama.LlamaFamilyRows(cfg),
                           draft_family=llama.LlamaFamilyRows(cfg))


def test_spec_requires_explicit_draft_family_for_non_gpt_draft():
    from dnn_tpu.models import llama

    cfg = llama.PRESETS["llama-test"]
    params = llama.init(jax.random.PRNGKey(26), cfg)
    prep = gpt.prepare_stacked(params, cfg)
    with pytest.raises(ValueError, match="draft_family"):
        SpeculativeBatcher(cfg, prep, cfg, prep, spec_k=2, slots=1,
                           max_len=48, prompt_pad=8,
                           family=llama.LlamaFamilyRows(cfg))
