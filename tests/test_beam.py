"""Beam search: deterministic highest-likelihood decoding.

Contracts:
  * beam_size=1 == greedy make_generate, token-for-token;
  * a wider beam never scores below greedy (sequence log-likelihood under
    teacher forcing is the oracle);
  * EOS freezes a beam: everything after its EOS is EOS, its score stops
    moving; return_all comes back best-first;
  * the length penalty only rescales selection, not the token math.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt
from dnn_tpu.runtime.beam import make_beam_generate
from dnn_tpu.runtime.generate import make_generate

CFG = gpt.GPTConfig(block_size=48, vocab_size=64, n_layer=2, n_head=4,
                    n_embd=32)


@pytest.fixture(scope="module")
def setup():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (3, 7), 0,
                             CFG.vocab_size, dtype=jnp.int32)
    return prepared, ids


def _seq_logprob(prepared, prompt, completion):
    """Teacher-forced log-likelihood of `completion` after `prompt`."""
    full = jnp.concatenate([prompt, completion], axis=1)
    logits = gpt.make_apply_stacked(CFG)(prepared, full)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    t = prompt.shape[1]
    # token at position t+j is predicted by logits at t+j-1
    pred = logp[:, t - 1:-1]
    picked = jnp.take_along_axis(pred, completion[..., None], axis=-1)[..., 0]
    return picked.sum(axis=-1)


def test_beam1_equals_greedy(setup):
    prepared, ids = setup
    greedy = make_generate(CFG, max_new_tokens=8)(
        prepared, ids, jax.random.PRNGKey(2))
    beam = make_beam_generate(CFG, max_new_tokens=8, beam_size=1)(
        prepared, ids)
    np.testing.assert_array_equal(np.asarray(beam), np.asarray(greedy))


def test_wider_beam_never_loses_to_greedy(setup):
    prepared, ids = setup
    greedy = make_generate(CFG, max_new_tokens=8)(
        prepared, ids, jax.random.PRNGKey(2))
    beam = make_beam_generate(CFG, max_new_tokens=8, beam_size=4)(
        prepared, ids)
    lp_g = np.asarray(_seq_logprob(prepared, ids, greedy))
    lp_b = np.asarray(_seq_logprob(prepared, ids, beam))
    assert (lp_b >= lp_g - 1e-4).all(), (lp_b, lp_g)


def test_beam_scores_are_true_logprobs(setup):
    """return_all scores (alpha=0) must equal the teacher-forced sequence
    log-likelihood of each hypothesis — the search bookkeeping (parent
    gathers, cache reordering) proves itself against the stateless oracle."""
    prepared, ids = setup
    toks, scores = make_beam_generate(
        CFG, max_new_tokens=6, beam_size=3, return_all=True)(prepared, ids)
    assert toks.shape == (3, 3, 6) and scores.shape == (3, 3)
    # best-first ordering
    s = np.asarray(scores)
    assert (np.diff(s, axis=1) <= 1e-6).all()
    for beam_i in range(3):
        want = np.asarray(_seq_logprob(prepared, ids, toks[:, beam_i]))
        np.testing.assert_allclose(s[:, beam_i], want, rtol=1e-4, atol=1e-4)


def test_eos_freezes_beam(setup):
    prepared, ids = setup
    # pick eos = the greedy first token, so the best beam finishes at once
    greedy = make_generate(CFG, max_new_tokens=8)(
        prepared, ids, jax.random.PRNGKey(2))
    eos = int(np.asarray(greedy)[0, 0])
    toks, scores = make_beam_generate(
        CFG, max_new_tokens=8, beam_size=3, eos_id=eos,
        return_all=True)(prepared, ids)
    t0 = np.asarray(toks)[0]
    finished_rows = [r for r in t0 if eos in r.tolist()]
    assert finished_rows, t0
    for r in finished_rows:
        first = r.tolist().index(eos)
        assert (r[first:] == eos).all(), r  # frozen: EOS forever after


def test_length_penalty_rescales_only(setup):
    prepared, ids = setup
    t0, s0 = make_beam_generate(
        CFG, max_new_tokens=6, beam_size=3, return_all=True)(prepared, ids)
    t1, s1 = make_beam_generate(
        CFG, max_new_tokens=6, beam_size=3, length_penalty=1.0,
        return_all=True)(prepared, ids)
    # no EOS -> all hypotheses share one length; the penalty divides every
    # score by the same constant and the ranking (hence tokens) is identical
    np.testing.assert_array_equal(np.asarray(t1), np.asarray(t0))
    lp = ((5.0 + 6.0) / 6.0) ** 1.0
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s0) / lp,
                               rtol=1e-5)


def test_rejects_bad_args(setup):
    with pytest.raises(ValueError, match="beam_size"):
        make_beam_generate(CFG, max_new_tokens=4, beam_size=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        make_beam_generate(CFG, max_new_tokens=0, beam_size=2)


def test_beam_llama_family_and_gemma2():
    """Beam search rides the LLaMA family (and Gemma-2's per-layer
    windows) through _family_fns: beam_size=1 == greedy make_generate,
    returned beams are score-sorted, and the best beam's rescored
    sum-logprob tracks greedy's.

    The old form asserted best-beam >= greedy - 1e-4, which is NOT a
    theorem: beam search is inadmissible — a kept prefix that outscores
    the greedy prefix mid-decode can finish worse, so the pruned greedy
    path may beat every surviving beam. On gemma2-test's random weights
    (near-flat logits, constant pruning pressure) that is exactly what
    happens, deterministically: best beam -40.703 vs greedy -40.618.
    The bound below allows the documented inadmissibility gap while
    still catching real scoring regressions (sign errors, wrong-step
    gathers land whole nats away)."""
    from dnn_tpu.models import llama

    for name in ("llama-test", "gemma2-test"):
        cfg = llama.PRESETS[name]
        params = llama.init(jax.random.PRNGKey(31), cfg)
        prepared = gpt.prepare_stacked(params, cfg)
        prompt = jnp.asarray(
            np.random.RandomState(32).randint(0, cfg.vocab_size, (1, 12)))
        n_new = 8
        greedy = np.asarray(llama.make_generate(cfg, max_new_tokens=n_new)(
            prepared, prompt, jax.random.PRNGKey(0)))
        b1 = np.asarray(make_beam_generate(cfg, max_new_tokens=n_new,
                                           beam_size=1)(prepared, prompt))
        np.testing.assert_array_equal(b1, greedy, err_msg=name)

        toks, scores = make_beam_generate(
            cfg, max_new_tokens=n_new, beam_size=4,
            return_all=True)(prepared, prompt)
        # internal scores come back best-first
        s = np.asarray(scores)[0]
        assert (np.diff(s) <= 1e-6).all(), name

        def seq_logprob(seq):
            ids = np.concatenate([np.asarray(prompt)[0], seq])
            logits = np.asarray(llama.make_apply(cfg)(
                params, jnp.asarray(ids[None, :-1])))[0]
            lp = jax.nn.log_softmax(jnp.asarray(logits), axis=-1)
            steps = range(len(ids) - n_new - 1, len(ids) - 1)
            return float(sum(lp[i, ids[i + 1]] for i in steps))

        # inadmissibility slack: 0.25 nats over 8 steps (observed gap
        # 0.084 on gemma2-test); a scoring bug is orders louder
        assert seq_logprob(np.asarray(toks)[0, 0]) >= \
            seq_logprob(greedy[0]) - 0.25, name
