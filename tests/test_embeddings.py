"""Embedding extraction (runtime/embeddings.py) and the daemon's embed
endpoint: hidden == HF last_hidden_state, pooling masks pads exactly,
and the gRPC front returns the same vectors the library computes.

The reference can only argmax-classify (node.py:186-192); representation
export is capability built beyond it."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama
from dnn_tpu.runtime.embeddings import make_embed

LCFG = llama.PRESETS["llama-test"]
GCFG = gpt.PRESETS["gpt2-test"]


def _lprep(seed=0):
    p = llama.init(jax.random.PRNGKey(seed), LCFG)
    return gpt.prepare_stacked(p, LCFG)


def test_hidden_matches_hf_last_hidden_state():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama.to_hf_config(LCFG, attn_implementation="eager")
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd)
    prepared = gpt.prepare_stacked(params, LCFG)
    ids = np.random.RandomState(0).randint(0, LCFG.vocab_size, (2, 10))
    with torch.no_grad():
        want = model.model(torch.from_numpy(ids)).last_hidden_state.numpy()
    got = np.asarray(make_embed(LCFG, pooling="none")(
        prepared, ids.astype(np.int32), np.asarray([10, 10], np.int32)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_gemma2_hidden_matches_hf():
    """The extractor rides every family switch — alternating windows,
    post-norms, (1+w) norms, embed scaling."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    cfg = llama.PRESETS["gemma2-test"]
    hf_cfg = llama.to_hf_config(cfg, attn_implementation="eager")
    torch.manual_seed(1)
    model = transformers.Gemma2ForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    from dnn_tpu.io.checkpoint import llama_params_from_state_dict

    params = llama_params_from_state_dict(sd, post_norms=True,
                                          tied_head="omit")
    prepared = gpt.prepare_stacked(params, cfg)
    ids = np.random.RandomState(1).randint(0, cfg.vocab_size, (1, 24))
    with torch.no_grad():
        want = model.model(torch.from_numpy(ids)).last_hidden_state.numpy()
    got = np.asarray(make_embed(cfg, pooling="none")(
        prepared, ids.astype(np.int32), np.asarray([24], np.int32)))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_pooling_masks_pads_exactly():
    """Pad invariance (causal attention) + pooling correctness: a padded
    batch row pools to the same vector as its unpadded solo run."""
    prepared = _lprep()
    rs = np.random.RandomState(2)
    a = rs.randint(0, LCFG.vocab_size, (7,))
    b = rs.randint(0, LCFG.vocab_size, (12,))
    ids = np.zeros((2, 12), np.int32)
    ids[0, :7] = a
    ids[0, 7:] = 99  # junk pads — must not matter
    ids[1] = b
    lengths = np.asarray([7, 12], np.int32)

    for pooling in ("mean", "last"):
        fn = make_embed(LCFG, pooling=pooling)
        batch = np.asarray(fn(prepared, ids, lengths))
        solo_a = np.asarray(fn(prepared, a[None].astype(np.int32),
                               np.asarray([7], np.int32)))[0]
        np.testing.assert_allclose(batch[0], solo_a, atol=1e-5, rtol=1e-5)

    # mean really is the masked mean of the "none" hidden states
    h = np.asarray(make_embed(LCFG, pooling="none")(prepared, ids, lengths))
    want_mean = h[0, :7].mean(axis=0)
    got_mean = np.asarray(make_embed(LCFG, pooling="mean")(
        prepared, ids, lengths))[0]
    np.testing.assert_allclose(got_mean, want_mean, atol=1e-5, rtol=1e-5)
    # last picks position length-1
    got_last = np.asarray(make_embed(LCFG, pooling="last")(
        prepared, ids, lengths))[0]
    np.testing.assert_allclose(got_last, h[0, 6], atol=1e-6)


def test_daemon_embed_endpoint():
    """Over real gRPC: NodeClient.embed == library make_embed on the
    same prepared params; bad pooling is INVALID_ARGUMENT."""
    import grpc

    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    params = gpt.init(jax.random.PRNGKey(3), GCFG)
    prepared = gpt.prepare_stacked(params, GCFG)
    port = 59277
    t, stop = start_lm_server_in_background(
        GCFG, prepared, port=port, slots=2, max_len=64, prompt_pad=16,
        default_max_new=4)
    try:
        client = NodeClient(f"127.0.0.1:{port}")
        assert client.wait_healthy(deadline=60)
        prompt = np.asarray([5, 3, 8, 13, 2], np.int32)
        for pooling in ("mean", "last"):
            got = client.embed(prompt, pooling=pooling)
            padded = np.zeros((1, 16), np.int32)
            padded[0, :5] = prompt
            want = np.asarray(make_embed(GCFG, pooling=pooling)(
                prepared, padded, np.asarray([5], np.int32)))[0]
            np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
        # generation still works on the same server (endpoint dispatch)
        toks = client.generate(prompt, max_new_tokens=4)
        assert len(toks) == 4
        with pytest.raises(grpc.RpcError):
            client.embed(prompt, pooling="bogus")
    finally:
        stop()
