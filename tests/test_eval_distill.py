"""evaluate() (held-out loss/perplexity) and distill_loss (teacher ->
student knowledge distillation) — the train-side helpers the
inference-only reference has no counterpart for (SURVEY §5)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt, llama

CFG = gpt.PRESETS["gpt2-test"]


def _tokens(rs, n, b=2, t=16):
    return [rs.randint(0, CFG.vocab_size, (b, t)) for _ in range(n)]


def test_evaluate_matches_manual_mean():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    apply = gpt.make_apply(CFG)
    batches = _tokens(np.random.RandomState(0), 3)
    out = train.evaluate(apply, params, iter(batches))
    # uniform shapes, no masking: token-weighted == mean of batch means
    want = float(np.mean([
        float(train.next_token_loss(apply, params, jnp.asarray(b)))
        for b in batches]))
    assert out["batches"] == 3
    assert out["tokens"] == 3 * 2 * 15
    assert out["loss"] == pytest.approx(want, rel=1e-6)
    assert out["perplexity"] == pytest.approx(float(np.exp(want)), rel=1e-5)
    with pytest.raises(ValueError, match="at least one"):
        train.evaluate(apply, params, iter([]))


def test_evaluate_is_token_weighted_under_masking():
    """Batches with different non-pad token counts weight by TOKENS, not
    by batch (a mean of means would bias toward the short batch)."""
    pad = 0
    params = gpt.init(jax.random.PRNGKey(1), CFG)
    apply = gpt.make_apply(CFG)
    rs = np.random.RandomState(2)
    short = rs.randint(1, CFG.vocab_size, (1, 16))
    short[0, 4:] = pad  # 3 non-pad targets
    full = rs.randint(1, CFG.vocab_size, (1, 16))  # 15 targets
    step = train.make_eval_step(apply, ignore_index=pad)
    sums = [tuple(map(float, step(params, jnp.asarray(b))))
            for b in (short, full)]
    want = sum(s for s, _ in sums) / sum(m for _, m in sums)
    out = train.evaluate(apply, params, iter([short, full]),
                         ignore_index=pad, eval_step=step)
    assert out["tokens"] == int(sum(m for _, m in sums))
    assert out["loss"] == pytest.approx(want, rel=1e-6)
    # and it differs from the biased mean-of-means
    biased = np.mean([s / m for s, m in sums])
    assert abs(out["loss"] - biased) > 1e-6


def test_distill_reduces_kl_to_teacher():
    """A few distillation steps must move the student's distribution
    toward the teacher's (average KL drops), and alpha=0 must equal the
    plain CE loss."""
    t_cfg = CFG
    s_cfg = gpt.GPTConfig(block_size=32, vocab_size=CFG.vocab_size,
                          n_layer=1, n_head=2, n_embd=32)
    teacher = gpt.init(jax.random.PRNGKey(1), t_cfg)
    student = gpt.init(jax.random.PRNGKey(2), s_cfg)
    t_apply, s_apply = gpt.make_apply(t_cfg), gpt.make_apply(s_cfg)

    tokens = jnp.asarray(
        np.random.RandomState(3).randint(0, CFG.vocab_size, (4, 16)))
    t_logits = t_apply(teacher, tokens[:, :-1])

    def kl_now(sp):
        s = jax.nn.log_softmax(
            s_apply(sp, tokens[:, :-1]).astype(jnp.float32), -1)
        t = jax.nn.log_softmax(t_logits.astype(jnp.float32), -1)
        return float(jnp.mean(jnp.sum(jnp.exp(t) * (t - s), -1)))

    loss_fn = lambda p, batch: train.distill_loss(  # noqa: E731
        s_apply, t_logits, p, batch, temperature=1.0, alpha=1.0)
    opt = optax.adam(1e-2)
    step = train.make_train_step(loss_fn, opt)
    state = opt.init(student)
    before = kl_now(student)
    for _ in range(12):
        student, state, _ = step(student, state, tokens)
    assert kl_now(student) < before * 0.9, "distillation must reduce KL"

    # alpha=0 is the plain hard loss
    hard = train.distill_loss(s_apply, t_logits, student, tokens, alpha=0.0)
    want = train.next_token_loss(s_apply, student, tokens)
    assert float(hard) == pytest.approx(float(want), rel=1e-6)


def test_distill_cross_family_teacher():
    """A LLaMA teacher distills into a GPT student — only the vocabs
    must match (the speculative-decoding contract, reused)."""
    l_cfg = llama.PRESETS["llama-test"]
    assert l_cfg.vocab_size == CFG.vocab_size
    teacher = llama.init(jax.random.PRNGKey(4), l_cfg)
    tokens = jnp.asarray(
        np.random.RandomState(5).randint(0, CFG.vocab_size, (2, 12)))
    t_logits = llama.make_apply(l_cfg)(teacher, tokens[:, :-1])
    student = gpt.init(jax.random.PRNGKey(6), CFG)
    loss = train.distill_loss(gpt.make_apply(CFG), t_logits, student,
                              tokens, temperature=2.0, alpha=0.5)
    assert np.isfinite(float(loss))
    with pytest.raises(ValueError, match="alpha"):
        train.distill_loss(gpt.make_apply(CFG), t_logits, student, tokens,
                           alpha=1.5)


def test_guards():
    params = gpt.init(jax.random.PRNGKey(7), CFG)
    apply = gpt.make_apply(CFG)
    tokens = jnp.asarray(np.full((1, 8), 5, np.int64))
    with pytest.raises(ValueError, match="temperature"):
        train.distill_loss(apply, apply(params, tokens[:, :-1]), params,
                           tokens, temperature=0.0)
    # every target == ignore_index: error, not a perfect score
    with pytest.raises(ValueError, match="non-ignored"):
        train.evaluate(apply, params, iter([np.asarray(tokens)]),
                       ignore_index=5)
