"""Qwen2-MoE (shared-expert MoE, models/llama_moe.py): Qwen2 attention
biases + fine-grained routed experts with RAW softmax top-k weights
(norm_topk_prob=false) + the always-on sigmoid-gated shared expert.

All three switches ride MixtralConfig fields through the family's one
ffn hook, so the dense forward, cached decode, batcher rows, and the EP
paths inherit them with no new runtime code — pinned against HF
Qwen2MoeForCausalLM and the framework's own cross-path contracts."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama, llama_moe

CFG = llama_moe.PRESETS["qwen2moe-test"]


def _params(seed=0):
    return llama_moe.init(jax.random.PRNGKey(seed), CFG)


def test_structure():
    p = _params()
    moe = p["h_0"]["moe"]
    assert moe["shared"]["gate"]["kernel"].shape == (CFG.n_embd,
                                                    CFG.d_shared)
    assert moe["shared_gate"]["kernel"].shape == (CFG.n_embd, 1)
    assert "bias" in p["h_0"]["attn"]["q"]  # Qwen2 biases
    assert not CFG.router_norm_topk


def test_hf_qwen2moe_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama_moe.to_hf_config(CFG, attn_implementation="eager")
    assert isinstance(hf_cfg, transformers.Qwen2MoeConfig)
    assert not hf_cfg.norm_topk_prob
    torch.manual_seed(0)
    model = transformers.Qwen2MoeForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    assert any("shared_expert_gate" in k for k in sd)
    params = llama_moe.params_from_state_dict(sd)  # layout auto-detected

    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 12))
    with torch.no_grad():
        out = model(torch.from_numpy(ids))
    want = out.logits.numpy()
    got = np.asarray(llama_moe.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=3e-3, rtol=3e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy cached decode == HF generate (router + shared expert per
    # decode step)
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 9))
    n_new = 10
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 9:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama_moe.make_generate(
        CFG, max_new_tokens=n_new)(prepared, jnp.asarray(prompt),
                                   jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_raw_topk_weights_differ_from_renormalized():
    """norm_topk_prob=False must actually change the math: the same
    weights under Mixtral-style renormalization produce different
    logits (guards against the flag being silently ignored)."""
    import dataclasses

    p = _params(seed=3)
    ids = np.random.RandomState(4).randint(0, CFG.vocab_size, (1, 8))
    raw = np.asarray(llama_moe.make_apply(CFG)(p, jnp.asarray(ids)))
    renorm_cfg = dataclasses.replace(CFG, router_norm_topk=True)
    renorm = np.asarray(llama_moe.make_apply(renorm_cfg)(
        p, jnp.asarray(ids)))
    assert not np.allclose(raw, renorm, atol=1e-5)


def test_batcher_matches_solo():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(seed=5)
    prepared = gpt.prepare_stacked(p, CFG)
    prompts = [np.asarray([3, 1, 4, 1, 5]), np.asarray([9, 2, 6, 5])]
    n_new = 6
    solo = llama_moe.make_generate(CFG, max_new_tokens=n_new)
    want = [np.asarray(solo(prepared, jnp.asarray(pr[None]),
                            jax.random.PRNGKey(0)))[0] for pr in prompts]
    srv = ContinuousBatcher(CFG, prepared, slots=2,
                            max_len=CFG.block_size, prompt_pad=8,
                            family=llama_moe.family_rows(CFG))
    rids = [srv.submit(pr, max_new_tokens=n_new) for pr in prompts]
    srv.drain()
    for rid, w in zip(rids, want):
        np.testing.assert_array_equal(srv.results[rid], w)


def test_ep_decode_matches_solo_grouped():
    """EP decode with the shared expert: routed experts shard + travel
    all_to_all, the shared expert computes locally on every device —
    greedy token parity with the solo grouped decoder."""
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    n = 4
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    p = _params(seed=6)
    prepared = gpt.prepare_stacked(p, CFG)
    prompt = np.random.RandomState(7).randint(0, CFG.vocab_size,
                                              (n * 2, 6))
    n_new = 5
    want = np.asarray(llama.make_generate(
        CFG, max_new_tokens=n_new,
        ffn=llama_moe.make_ffn(CFG, groups=n))(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(8)))
    got = np.asarray(llama_moe.make_generate_ep(
        CFG, mesh, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(8)))
    np.testing.assert_array_equal(got, want)


def test_ep_forward_and_ep_pp_decode_match_grouped():
    """The remaining EP builders with the shared expert: make_apply_ep
    (logit parity incl. the replicated shared leaves) and the EP x PP 2D
    decoder (stage-stacked shared kernels under _ep_param_spec's
    stage_axis handling) — both vs the solo grouped oracle."""
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    p = _params(seed=9)
    ids = np.random.RandomState(10).randint(0, CFG.vocab_size, (4, 8))
    mesh = make_mesh({EXPERT_AXIS: 4}, jax.devices()[:4])
    want = np.asarray(llama.make_apply(
        CFG, ffn=llama_moe.make_ffn(CFG, groups=4))(p, jnp.asarray(ids)))
    got = np.asarray(llama_moe.make_apply_ep(CFG, mesh)(
        p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    stages, n_exp = 3, 2
    mesh2 = make_mesh({STAGE_AXIS: stages, EXPERT_AXIS: n_exp},
                      jax.devices()[:stages * n_exp])
    prepared = gpt.prepare_stacked(p, CFG)
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG, mesh2)
    prompt = np.random.RandomState(11).randint(0, CFG.vocab_size,
                                               (n_exp * 2, 6))
    n_new = 5
    want_t = np.asarray(llama.make_generate(
        CFG, max_new_tokens=n_new,
        ffn=llama_moe.make_ffn(CFG, groups=n_exp))(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(12)))
    got_t = np.asarray(llama_moe.make_pipeline_generate_ep(
        CFG, mesh2, max_new_tokens=n_new)(
        stage_blocks, aux, jnp.asarray(prompt), jax.random.PRNGKey(12)))
    np.testing.assert_array_equal(got_t, want_t)


def test_registry_registered():
    from dnn_tpu.registry import get_model

    spec = get_model("qwen15-moe-a2.7b")
    assert spec.config.d_shared == 5632
    assert spec.config.n_expert == 60
