"""Multi-LoRA serving: per-request adapter selection in the continuous
batcher.

The contract extends the batcher's core one (a request's stream equals
its solo run): a request naming adapter i must produce EXACTLY the
tokens of a solo run on `merge_lora(base, adapter_i)` — whatever mix of
adapters shares the pool — and base requests must be bit-identical to a
server with no adapters at all. The view mechanism (lora.lora_view +
the delta inside ops.nn.linear) is also checked at the op level against
the merge, including over an int8-quantized base (the QLoRA-style
deployment: one quantized base, float adapters per tenant).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import lora
from dnn_tpu.models import gpt, llama
from dnn_tpu.ops.nn import linear
from dnn_tpu.runtime.generate import make_generate
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]


def _adapters(prepared, seeds, rank=4):
    """Random NON-trivial adapters against the prepared layout (init_lora
    zeroes b, which would make every test a tautology — randomize it)."""
    out = []
    for s in seeds:
        ad = lora.init_lora(jax.random.PRNGKey(s), prepared, rank=rank)
        # randomize the b half so the adapter actually changes the model
        ks = jax.random.split(jax.random.PRNGKey(100 + s), len(ad))
        for (p, ab), k in zip(sorted(ad.items()), ks):
            ab["b"] = jax.random.normal(k, ab["b"].shape) * 0.02
        out.append(ad)
    return out


@pytest.fixture(scope="module")
def setup():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    adapters = _adapters(prepared, seeds=(1, 2))
    return prepared, adapters


def _solo(cfg, prepared, prompt, n):
    fn = make_generate(cfg, max_new_tokens=n)
    out = fn(prepared, jnp.asarray(prompt, jnp.int32)[None, :],
             jax.random.PRNGKey(9))
    return np.asarray(out)[0]


def test_adapter_request_matches_solo_merged(setup):
    prepared, adapters = setup
    prompt = np.arange(1, 9) % CFG.vocab_size
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                            prompt_pad=16, lora_adapters=adapters)
    rid = srv.submit(prompt, max_new_tokens=10, adapter=0)
    res = srv.drain()
    merged = lora.merge_lora(prepared, adapters[0])
    np.testing.assert_array_equal(res[rid], _solo(CFG, merged, prompt, 10))


def test_mixed_pool_each_adapter_isolated(setup):
    """Base + two different adapters decode TOGETHER; each stream equals
    its own solo reference — the feature's whole point."""
    prepared, adapters = setup
    p1 = (np.arange(1, 7) * 3) % CFG.vocab_size
    p2 = (np.arange(1, 10) * 5) % CFG.vocab_size
    p3 = (np.arange(1, 5) * 7) % CFG.vocab_size
    srv = ContinuousBatcher(CFG, prepared, slots=3, max_len=64,
                            prompt_pad=16, lora_adapters=adapters)
    r1 = srv.submit(p1, max_new_tokens=8, adapter=0)
    r2 = srv.submit(p2, max_new_tokens=8, adapter=1)
    r3 = srv.submit(p3, max_new_tokens=8)  # base model
    res = srv.drain()
    np.testing.assert_array_equal(
        res[r1], _solo(CFG, lora.merge_lora(prepared, adapters[0]), p1, 8))
    np.testing.assert_array_equal(
        res[r2], _solo(CFG, lora.merge_lora(prepared, adapters[1]), p2, 8))
    np.testing.assert_array_equal(res[r3], _solo(CFG, prepared, p3, 8))


def test_base_requests_identical_to_plain_server(setup):
    """lora_adapters= must not perturb base-model requests at all."""
    prepared, adapters = setup
    prompt = np.arange(2, 11) % CFG.vocab_size
    with_lora = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                                  prompt_pad=16, lora_adapters=adapters)
    plain = ContinuousBatcher(CFG, prepared, slots=2, max_len=64,
                              prompt_pad=16)
    ra = with_lora.submit(prompt, max_new_tokens=9)
    rb = plain.submit(prompt, max_new_tokens=9)
    np.testing.assert_array_equal(with_lora.drain()[ra], plain.drain()[rb])


def test_slot_reuse_across_adapters(setup):
    """A slot that served adapter 0 must serve adapter 1 (and base)
    correctly afterwards — no stale delta leaks through reuse."""
    prepared, adapters = setup
    prompt = np.arange(1, 8) % CFG.vocab_size
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            prompt_pad=16, lora_adapters=adapters)
    r0 = srv.submit(prompt, max_new_tokens=6, adapter=0)
    res0 = dict(srv.drain())
    r1 = srv.submit(prompt, max_new_tokens=6, adapter=1)
    res1 = dict(srv.drain())
    r2 = srv.submit(prompt, max_new_tokens=6)
    res2 = dict(srv.drain())
    np.testing.assert_array_equal(
        res0[r0], _solo(CFG, lora.merge_lora(prepared, adapters[0]), prompt, 6))
    np.testing.assert_array_equal(
        res1[r1], _solo(CFG, lora.merge_lora(prepared, adapters[1]), prompt, 6))
    np.testing.assert_array_equal(res2[r2], _solo(CFG, prepared, prompt, 6))


def test_llama_family_multilora():
    lcfg = llama.PRESETS["llama-test"]
    params = llama.init(jax.random.PRNGKey(3), lcfg)
    prepared = gpt.prepare_stacked(params, lcfg)
    adapters = _adapters(prepared, seeds=(4,))
    prompt = np.array([5, 3, 7, 1, 2])
    srv = ContinuousBatcher(lcfg, prepared, slots=2, max_len=32,
                            prompt_pad=8, family=llama.LlamaFamilyRows(lcfg),
                            lora_adapters=adapters)
    rid = srv.submit(prompt, max_new_tokens=6, adapter=0)
    res = srv.drain()
    merged = lora.merge_lora(prepared, adapters[0])
    want = np.asarray(llama.make_generate(lcfg, max_new_tokens=6)(
        merged, jnp.asarray(prompt, jnp.int32)[None, :],
        jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(res[rid], want)


def test_prefix_cache_keys_by_adapter(setup):
    """K/V depends on the weights that produced it: an adapted request
    must not reuse a base-model prefix entry (or vice versa), while a
    same-adapter resubmission must hit."""
    prepared, adapters = setup
    prompt = np.arange(1, 33) % CFG.vocab_size  # two full 16-chunks
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=64,
                            prompt_pad=16, lora_adapters=adapters,
                            prefix_cache=8)
    r_base = srv.submit(prompt, max_new_tokens=4)
    srv.drain()
    assert srv.prefix_hits == 0
    r_ad = srv.submit(prompt, max_new_tokens=4, adapter=0)
    srv.drain()
    assert srv.prefix_hits == 0, "adapted request reused a base prefix!"
    r_ad2 = srv.submit(prompt, max_new_tokens=4, adapter=0)
    res = srv.drain()
    assert srv.prefix_hits == 1, "same-adapter resubmission should hit"
    merged = lora.merge_lora(prepared, adapters[0])
    np.testing.assert_array_equal(res[r_ad2],
                                  _solo(CFG, merged, prompt, 4))


def test_quantized_base_with_adapter_op_level(setup):
    """QLoRA-style: the delta applies on top of an int8 base linear —
    linear(quantized + lora view) == linear(quantized) + x @ a @ b."""
    from dnn_tpu.quant import quantize_tensor

    rng = np.random.RandomState(0)
    w = jnp.asarray(rng.randn(32, 48), jnp.float32)
    x = jnp.asarray(rng.randn(2, 3, 32), jnp.float32)
    a = jnp.asarray(rng.randn(2, 32, 4), jnp.float32) * 0.1  # N=2 adapters
    b = jnp.asarray(rng.randn(2, 4, 48), jnp.float32) * 0.1
    sel = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])  # row 0 -> ad 0, row 1 -> ad 1
    q, scale = quantize_tensor(w)
    qp = {"q": q, "scale": scale}
    base = linear(qp, x)
    got = linear({**qp, "lora": {"a": a, "b": b, "sel": sel}}, x)
    want = base + jnp.stack([x[0] @ a[0] @ b[0], x[1] @ a[1] @ b[1]])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)


def test_adapter_validation(setup):
    prepared, adapters = setup
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=32,
                            prompt_pad=8, lora_adapters=adapters)
    with pytest.raises(ValueError, match="out of range"):
        srv.submit(np.array([1, 2]), max_new_tokens=2, adapter=2)
    plain = ContinuousBatcher(CFG, prepared, slots=1, max_len=32,
                              prompt_pad=8)
    with pytest.raises(ValueError, match="lora_adapters"):
        plain.submit(np.array([1, 2]), max_new_tokens=2, adapter=0)


def test_speculative_rejects_lora(setup):
    from dnn_tpu.runtime.serving_spec import SpeculativeBatcher

    prepared, adapters = setup
    with pytest.raises(ValueError, match="lora_adapters"):
        SpeculativeBatcher(CFG, prepared, CFG, prepared,
                           lora_adapters=adapters)


def test_multilora_over_tp_sharded_base(setup):
    """Multi-LoRA composes with tensor parallelism: the base weights stay
    4-way model-sharded (gpt_tp_specs_stacked placement) while the tiny
    replicated adapter deltas apply per slot — GSPMD partitions the step
    programs from the base leaf shardings, and every stream still equals
    the unsharded merged reference."""
    from dnn_tpu import train
    from dnn_tpu.parallel.mesh import MODEL_AXIS, make_mesh

    prepared, adapters = setup
    mesh = make_mesh({MODEL_AXIS: 4}, jax.devices()[:4])
    specs = train.gpt_tp_specs_stacked(prepared)
    tp_prep = train.shard_pytree(prepared, mesh, specs)

    prompt = np.arange(1, 9) % CFG.vocab_size
    srv = ContinuousBatcher(CFG, tp_prep, slots=2, max_len=64,
                            prompt_pad=16, lora_adapters=adapters)
    r0 = srv.submit(prompt, max_new_tokens=8, adapter=0)
    r1 = srv.submit(prompt, max_new_tokens=8)  # base, same pool
    res = srv.drain()
    merged = lora.merge_lora(prepared, adapters[0])
    np.testing.assert_array_equal(res[r0], _solo(CFG, merged, prompt, 8))
    np.testing.assert_array_equal(res[r1], _solo(CFG, prepared, prompt, 8))


def test_trained_artifact_serves_through_stacked_layout(tmp_path):
    """The full deployment round trip: adapters trained against PER-LAYER
    params (the training layout), saved/loaded as npz, converted with
    adapters_to_stacked, served per-request — tokens equal the offline
    merge of the original artifact."""
    params = gpt.init(jax.random.PRNGKey(5), CFG)
    per_layer = lora.init_lora(jax.random.PRNGKey(6), params, rank=4)
    ks = jax.random.split(jax.random.PRNGKey(7), len(per_layer))
    for (p, ab), k in zip(sorted(per_layer.items()), ks):
        ab["b"] = jax.random.normal(k, ab["b"].shape) * 0.02
    f = str(tmp_path / "ad.npz")
    lora.save_lora(f, per_layer, alpha=8.0)
    loaded, alpha = lora.load_lora(f)
    assert alpha == 8.0
    stacked_ad = lora.adapters_to_stacked(loaded, CFG.n_layer)

    prepared = gpt.prepare_stacked(params, CFG)
    prompt = np.arange(3, 11) % CFG.vocab_size
    srv = ContinuousBatcher(CFG, prepared, slots=1, max_len=48,
                            prompt_pad=16, lora_adapters=[stacked_ad],
                            lora_alphas=[alpha])
    rid = srv.submit(prompt, max_new_tokens=8, adapter=0)
    got = srv.drain()[rid]
    # offline reference: merge in the TRAINING layout, then stack
    merged = gpt.prepare_stacked(
        lora.merge_lora(params, per_layer, alpha=alpha), CFG)
    np.testing.assert_array_equal(got, _solo(CFG, merged, prompt, 8))


def test_adapters_to_stacked_rejects_partial():
    params = gpt.init(jax.random.PRNGKey(8), CFG)
    per_layer = lora.init_lora(jax.random.PRNGKey(9), params, rank=2)
    partial = {k: v for k, v in per_layer.items() if not k.startswith("h_0")}
    with pytest.raises(ValueError, match="covers layers"):
        lora.adapters_to_stacked(partial, CFG.n_layer)


def test_stack_loras_validation(setup):
    prepared, adapters = setup
    with pytest.raises(ValueError, match="at least one"):
        lora.stack_loras([])
    bad = {k: v for k, v in list(adapters[0].items())[:-1]}
    with pytest.raises(ValueError, match="different leaves"):
        lora.stack_loras([adapters[0], bad])


def test_embedding_adapter_rejected_for_serving(setup):
    """An embedding-targeted adapter cannot be applied per-request (the
    delta lives in linear layers); the view must refuse rather than
    silently serve base embeddings."""
    prepared, _ = setup
    ad = lora.init_lora(jax.random.PRNGKey(11), prepared, rank=2,
                        targets=("wte",))
    stacked = lora.stack_loras([ad])
    sel = jnp.asarray([[1.0, 0.0]])
    with pytest.raises(ValueError, match="embedding"):
        lora.lora_view(prepared, stacked, sel)


def test_cli_serve_adapter_requires_serve_lm(tmp_path):
    """--serve_adapter outside --serve_lm must error, not silently serve
    the base model (the CLI's no-silent-drop rule)."""
    import json

    from dnn_tpu.node import main

    cfg = {"nodes": [{"id": "n0", "part_index": 0}], "num_parts": 1,
           "model": "gpt2-test"}
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(cfg))
    rc = main(["--node_id", "n0", "--config", str(cfg_path),
               "--generate", "4", "--serve_adapter", "whatever.npz"])
    assert rc == 1
