"""Tensor-parallel serving: the KV-cache generation path runs sharded over
the "model" mesh axis via GSPMD (place the stacked params with
gpt_tp_specs_stacked, jit does the rest). Invariant: TP == single-device."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import MODEL_AXIS, make_mesh
from dnn_tpu.runtime import generate as gen

CFG = gpt.PRESETS["gpt2-test"]


def _tp_prepared(mesh):
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    specs = train.gpt_tp_specs_stacked(prepared)
    return prepared, train.shard_pytree(prepared, mesh, specs), specs


def test_stacked_specs_shard_expected_leaves():
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh({MODEL_AXIS: 4}, jax.devices()[:4])
    _, tp_prep, specs = _tp_prepared(mesh)
    assert specs["blocks"]["attn"]["qkv"]["kernel"] == P(None, None, MODEL_AXIS)
    assert specs["blocks"]["mlp"]["proj"]["kernel"] == P(None, MODEL_AXIS, None)
    assert specs["wte"]["embedding"] == P(MODEL_AXIS, None)
    assert specs["lm_head"]["kernel"] == P(None, MODEL_AXIS)
    assert specs["blocks"]["ln_1"]["scale"] == P()
    q = tp_prep["blocks"]["attn"]["qkv"]["kernel"]
    assert q.sharding.spec == specs["blocks"]["attn"]["qkv"]["kernel"]


def test_tp_forward_with_cache_matches_single():
    mesh = make_mesh({MODEL_AXIS: 4}, jax.devices()[:4])
    prepared, tp_prep, _ = _tp_prepared(mesh)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0, CFG.vocab_size,
                             dtype=jnp.int32)
    cache = gen.init_cache(CFG, 2, 16)

    def fwd(p, i, c):
        return gen.forward_with_cache(p, i, c, 0, cfg=CFG)

    logits_ref, cache_ref = jax.jit(fwd)(prepared, ids, cache)
    logits_tp, cache_tp = jax.jit(fwd)(tp_prep, ids, cache)
    np.testing.assert_allclose(
        np.asarray(logits_tp), np.asarray(logits_ref), atol=1e-4, rtol=1e-4
    )
    np.testing.assert_allclose(
        np.asarray(cache_tp["k"]), np.asarray(cache_ref["k"]), atol=1e-4, rtol=1e-4
    )


def test_tp_batcher_matches_plain_batcher():
    """TP serving through the CONTINUOUS BATCHER: tensor-sharded prepared
    params drop straight in — GSPMD partitions the batcher's three step
    programs from the leaf shardings, no batcher changes — and every
    request's tokens equal the unsharded pool's."""
    from dnn_tpu.runtime.serving import ContinuousBatcher

    mesh = make_mesh({MODEL_AXIS: 4}, jax.devices()[:4])
    prepared, tp_prep, _ = _tp_prepared(mesh)
    prompts = [np.asarray(jax.random.randint(
        jax.random.PRNGKey(10 + i), (6 + i,), 0, CFG.vocab_size,
        dtype=jnp.int32)) for i in range(3)]

    def run(p):
        srv = ContinuousBatcher(CFG, p, slots=3, max_len=32, prompt_pad=8)
        rids = [srv.submit(prompts[0], max_new_tokens=6),
                srv.submit(prompts[1], max_new_tokens=4, seed=3,
                           temperature=0.9, top_k=9),
                srv.submit(prompts[2], max_new_tokens=5)]
        out = srv.drain()
        return [out[r] for r in rids]

    for a, b in zip(run(tp_prep), run(prepared)):
        np.testing.assert_array_equal(a, b)


def test_tp_generate_matches_single():
    mesh = make_mesh({MODEL_AXIS: 4}, jax.devices()[:4])
    prepared, tp_prep, _ = _tp_prepared(mesh)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, CFG.vocab_size,
                             dtype=jnp.int32)
    gen_fn = gen.make_generate(CFG, max_new_tokens=12)  # greedy
    rng = jax.random.PRNGKey(7)
    toks_ref = np.asarray(gen_fn(prepared, ids, rng))
    toks_tp = np.asarray(gen_fn(tp_prep, ids, rng))
    assert toks_ref.shape == (2, 12)
    np.testing.assert_array_equal(toks_tp, toks_ref)
