"""Mixtral (LLaMA block + sparse MoE MLP, models/llama_moe.py): HF
parity at no-drop capacity, cached-decode and batcher parity via the
llama `ffn` hook, and the capacity-drop fallback."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, llama_moe

CFG = llama_moe.PRESETS["mixtral-test"]


def _params(seed=0):
    return llama_moe.init(jax.random.PRNGKey(seed), CFG)


def test_structure():
    p = _params()
    blk = p["h_0"]
    assert "mlp" not in blk and "moe" in blk
    assert blk["moe"]["wg"].shape == (CFG.n_expert, CFG.n_embd, CFG.d_ff)
    assert blk["moe"]["router"]["kernel"].shape == (CFG.n_embd,
                                                   CFG.n_expert)
    assert "lm_head" in p  # mixtral does not tie


def test_hf_mixtral_parity():
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    hf_cfg = llama_moe.to_hf_config(CFG, attn_implementation="eager")
    torch.manual_seed(0)
    model = transformers.MixtralForCausalLM(hf_cfg).eval()
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    params = llama_moe.params_from_state_dict(sd)

    ids = np.random.RandomState(1).randint(0, CFG.vocab_size, (2, 16))
    with torch.no_grad():
        want = model(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama_moe.make_apply(CFG)(params, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=3e-3, rtol=3e-3)
    np.testing.assert_array_equal(got.argmax(-1), want.argmax(-1))

    # greedy cached decode == HF generate (experts route per decode step)
    prompt = np.random.RandomState(2).randint(0, CFG.vocab_size, (1, 9))
    n_new = 10
    with torch.no_grad():
        hf_out = model.generate(torch.from_numpy(prompt),
                                max_new_tokens=n_new, do_sample=False,
                                pad_token_id=0)
    want_toks = hf_out.numpy()[0, 9:]
    prepared = gpt.prepare_stacked(params, CFG)
    got_toks = np.asarray(llama_moe.make_generate(
        CFG, max_new_tokens=n_new)(prepared, jnp.asarray(prompt),
                                   jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got_toks, want_toks)


def test_generate_matches_stepwise_forward():
    p = _params(seed=3)
    prepared = gpt.prepare_stacked(p, CFG)
    apply = llama_moe.make_apply(CFG)
    prompt = np.random.RandomState(4).randint(0, CFG.vocab_size, (1, 8))
    n_new = 8
    ids = list(prompt[0])
    for _ in range(n_new):
        logits = np.asarray(apply(p, jnp.asarray([ids])))
        ids.append(int(logits[0, -1].argmax()))
    want = np.asarray(ids[len(prompt[0]):])
    got = np.asarray(llama_moe.make_generate(CFG, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(got, want)


def test_batcher_matches_solo():
    from dnn_tpu.runtime.serving import ContinuousBatcher

    p = _params(seed=5)
    prepared = gpt.prepare_stacked(p, CFG)
    prompts = [np.asarray([3, 1, 4, 1, 5]), np.asarray([9, 2, 6, 5, 3,
                                                        5, 8, 9])]
    n_new = 7
    solo = llama_moe.make_generate(CFG, max_new_tokens=n_new)
    want = [np.asarray(solo(prepared, jnp.asarray(pr[None]),
                            jax.random.PRNGKey(0)))[0] for pr in prompts]
    srv = ContinuousBatcher(CFG, prepared, slots=2, max_len=CFG.block_size,
                            prompt_pad=8,
                            family=llama_moe.family_rows(CFG))
    rids = [srv.submit(pr, max_new_tokens=n_new) for pr in prompts]
    srv.drain()
    for rid, w in zip(rids, want):
        np.testing.assert_array_equal(srv.results[rid], w)


def test_capacity_drop_degrades_to_residual():
    """A starved capacity factor must still run (dropped tokens pass
    through on the residual) and change the output vs full capacity."""
    p = _params(seed=6)
    tight = dataclasses.replace(CFG, capacity_factor=0.25)
    ids = np.random.RandomState(7).randint(0, CFG.vocab_size, (2, 16))
    full = np.asarray(llama_moe.make_apply(CFG)(p, jnp.asarray(ids)))
    dropped = np.asarray(llama_moe.make_apply(tight)(p, jnp.asarray(ids)))
    assert np.isfinite(dropped).all()
    assert np.abs(full - dropped).max() > 1e-6


def test_registry_and_partition_compose():
    """Multi-stage relay partitioning works like any llama family — the
    stage scan resolves the expert hook from the config."""
    from dnn_tpu.registry import get_model

    spec = get_model("mixtral-test")
    p = spec.init(jax.random.PRNGKey(8))
    ids = np.random.RandomState(9).randint(0, CFG.vocab_size, (1, 8))
    out = np.asarray(spec.apply(p, jnp.asarray(ids)))
    assert out.shape == (1, 8, CFG.vocab_size)
    for parts in (2, 3):
        x = jnp.asarray(ids)
        for st in spec.partition(parts):
            x = st.apply(st.slice_params(p), x)
        np.testing.assert_allclose(np.asarray(x), out, atol=1e-5,
                                   rtol=1e-5)


def test_config_resolved_hook_reaches_every_dispatcher():
    """Beam, the embedding extractor, and plain llama.make_apply must
    all work on Mixtral params WITHOUT llama_moe-specific wiring —
    MixtralConfig.default_ffn is the one resolution point."""
    from dnn_tpu.models import llama
    from dnn_tpu.runtime.beam import make_beam_generate
    from dnn_tpu.runtime.embeddings import make_embed

    p = _params(seed=10)
    prepared = gpt.prepare_stacked(p, CFG)
    ids = np.random.RandomState(11).randint(0, CFG.vocab_size, (1, 8))

    # plain llama entry points resolve the hook from the config
    via_llama = np.asarray(llama.make_apply(CFG)(p, jnp.asarray(ids)))
    via_moe = np.asarray(llama_moe.make_apply(CFG)(p, jnp.asarray(ids)))
    np.testing.assert_array_equal(via_llama, via_moe)

    greedy = np.asarray(llama_moe.make_generate(CFG, max_new_tokens=5)(
        prepared, jnp.asarray(ids), jax.random.PRNGKey(0)))
    b1 = np.asarray(make_beam_generate(CFG, max_new_tokens=5,
                                       beam_size=1)(prepared,
                                                    jnp.asarray(ids)))
    np.testing.assert_array_equal(b1, greedy)

    vec = np.asarray(make_embed(CFG, pooling="mean")(
        prepared, ids.astype(np.int32), np.asarray([8], np.int32)))
    assert vec.shape == (1, CFG.n_embd) and np.isfinite(vec).all()

    # seq/pipeline paths reject MoE explicitly rather than mis-routing
    from dnn_tpu.parallel.mesh import SEQ_AXIS, make_mesh

    mesh = make_mesh({SEQ_AXIS: jax.device_count()})
    with pytest.raises(ValueError, match="MoE"):
        llama.make_apply_seq_parallel(CFG, mesh)
    with pytest.raises(ValueError, match="MoE"):
        llama.LlamaPipelineFamily(CFG)


def test_ep_matches_grouped_dense():
    """Expert-parallel Mixtral over the expert axis == the dense forward
    with matching routing groups (the GShard parity contract, llama-MoE
    edition): tokens cross devices via all_to_all, logits must be
    identical."""
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    n = 4
    assert CFG.n_expert % n == 0
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    p = _params(seed=12)
    ids = np.random.RandomState(13).randint(0, CFG.vocab_size, (n * 2, 8))

    want = np.asarray(llama.make_apply(
        CFG, ffn=llama_moe.make_ffn(CFG, groups=n))(p, jnp.asarray(ids)))
    got = np.asarray(llama_moe.make_apply_ep(CFG, mesh)(
        p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)

    with pytest.raises(ValueError, match="divisible"):
        llama_moe.make_apply_ep(CFG, mesh)(p, jnp.asarray(ids[:3]))


def test_ep_decode_matches_solo_grouped():
    """EP KV-cache generation == the solo decoder with matching routing
    groups, token-for-token (greedy) — the GPT-MoE family's EP decode
    parity contract (tests/test_generate_moe.py) extended to Mixtral."""
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    n = 4
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    p = _params(seed=16)
    prepared = gpt.prepare_stacked(p, CFG)
    prompt = np.random.RandomState(17).randint(0, CFG.vocab_size, (n * 2, 6))
    n_new = 5
    want = np.asarray(llama.make_generate(
        CFG, max_new_tokens=n_new, ffn=llama_moe.make_ffn(CFG, groups=n))(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(18)))
    got = np.asarray(llama_moe.make_generate_ep(
        CFG, mesh, max_new_tokens=n_new)(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(18)))
    np.testing.assert_array_equal(got, want)

    with pytest.raises(ValueError, match="divisible"):
        llama_moe.make_generate_ep(CFG, mesh, max_new_tokens=2)(
            prepared, jnp.asarray(prompt[:3]), jax.random.PRNGKey(0))


def test_ep_pp_decode_matches_solo_grouped():
    """EP x PP 2D Mixtral decode ({stage, expert} mesh: all_to_all expert
    dispatch inside every stage-ring sub-step) == the solo decoder with
    matching routing groups, token-for-token."""
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked

    stages, n_exp = 3, 2  # n_layer=3 stages x 2 expert columns
    assert CFG.n_layer % stages == 0 and CFG.n_expert % n_exp == 0
    mesh = make_mesh({STAGE_AXIS: stages, EXPERT_AXIS: n_exp},
                     jax.devices()[:stages * n_exp])
    p = _params(seed=19)
    prepared = gpt.prepare_stacked(p, CFG)
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG, mesh)
    prompt = np.random.RandomState(20).randint(0, CFG.vocab_size,
                                               (n_exp * 2, 6))
    n_new = 5
    want = np.asarray(llama.make_generate(
        CFG, max_new_tokens=n_new,
        ffn=llama_moe.make_ffn(CFG, groups=n_exp))(
        prepared, jnp.asarray(prompt), jax.random.PRNGKey(21)))
    got = np.asarray(llama_moe.make_pipeline_generate_ep(
        CFG, mesh, max_new_tokens=n_new)(
        stage_blocks, aux, jnp.asarray(prompt), jax.random.PRNGKey(21)))
    np.testing.assert_array_equal(got, want)


def test_ep_handles_config_variants():
    """The EP spec derives from the real pytree: a q/k/v-biased Mixtral
    variant (extra bias leaves) shards and matches the grouped dense
    forward instead of tripping a hardcoded-structure mismatch."""
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    biased = dataclasses.replace(CFG, attn_bias=True)
    n = 4
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    p = llama_moe.init(jax.random.PRNGKey(14), biased)
    assert "bias" in p["h_0"]["attn"]["q"]
    ids = np.random.RandomState(15).randint(0, biased.vocab_size, (n, 8))
    want = np.asarray(llama.make_apply(
        biased, ffn=llama_moe.make_ffn(biased, groups=n))(
        p, jnp.asarray(ids)))
    got = np.asarray(llama_moe.make_apply_ep(biased, mesh)(
        p, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_int8_expert_stacks():
    """quantize_tree recognizes the gated stacks; the int8 expert triple
    dequantizes in the epilogue — forward stays close to f32, greedy
    decode heads agree, and EP shards the scale leaves."""
    from dnn_tpu import quant
    from dnn_tpu.models import llama
    from dnn_tpu.parallel.mesh import EXPERT_AXIS, make_mesh

    p = _params(seed=16)
    q = quant.quantize_tree(p)
    moe_q = q["h_0"]["moe"]
    assert moe_q["wg"].dtype == jnp.int8 and "wg_scale" in moe_q
    assert moe_q["router"]["kernel"].dtype != jnp.int8, "router stays f32"

    ids = np.random.RandomState(17).randint(0, CFG.vocab_size, (2, 12))
    f32 = np.asarray(llama_moe.make_apply(CFG)(p, jnp.asarray(ids)))
    i8 = np.asarray(llama_moe.make_apply(CFG)(q, jnp.asarray(ids)))
    # int8 rounding noise (attention kernels quantize too under the
    # default predicate), but the distribution must track
    assert np.abs(f32 - i8).max() < 0.6
    agree = (f32.argmax(-1) == i8.argmax(-1)).mean()
    assert agree > 0.8, f"argmax agreement {agree}"

    # greedy decode runs end-to-end on the quantized stacks
    prep_q = gpt.prepare_stacked(q, CFG)
    toks = np.asarray(llama_moe.make_generate(CFG, max_new_tokens=6)(
        prep_q, jnp.asarray(ids[:1, :6]), jax.random.PRNGKey(0)))[0]
    assert toks.shape == (6,)

    # quantizing AFTER stacking works too (the 4-D (L, E, D, F) form):
    # same decode trajectory as quantize-then-stack
    q_stacked = quant.quantize_tree(gpt.prepare_stacked(p, CFG))
    assert q_stacked["blocks"]["moe"]["wg"].dtype == jnp.int8
    assert q_stacked["blocks"]["moe"]["wg_scale"].ndim == 4
    toks2 = np.asarray(llama_moe.make_generate(CFG, max_new_tokens=6)(
        q_stacked, jnp.asarray(ids[:1, :6]), jax.random.PRNGKey(0)))[0]
    np.testing.assert_array_equal(toks2, toks)

    # EP over int8 stacks: pytree-derived spec shards the scales too
    n = 4
    mesh = make_mesh({EXPERT_AXIS: n}, jax.devices()[:n])
    want = np.asarray(llama.make_apply(
        CFG, ffn=llama_moe.make_ffn(CFG, groups=n))(q, jnp.asarray(
            np.tile(ids, (2, 1)))))
    got = np.asarray(llama_moe.make_apply_ep(CFG, mesh)(
        q, jnp.asarray(np.tile(ids, (2, 1)))))
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-5)


def test_quantize_tree_idempotent_on_expert_stacks():
    """Re-quantizing an already-int8 tree must be a no-op — without the
    dtype/scale guard it would overwrite the real expert scales with
    ~1.0 (amax of int8) and silently corrupt the model."""
    from dnn_tpu import quant

    p = _params(seed=20)
    q1 = quant.quantize_tree(p)
    q2 = quant.quantize_tree(q1)
    s1 = q1["h_0"]["moe"]["wg_scale"]
    s2 = q2["h_0"]["moe"]["wg_scale"]
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    ids = np.random.RandomState(21).randint(0, CFG.vocab_size, (1, 8))
    np.testing.assert_array_equal(
        np.asarray(llama_moe.make_apply(CFG)(q1, jnp.asarray(ids))),
        np.asarray(llama_moe.make_apply(CFG)(q2, jnp.asarray(ids))))
