"""FSDP / ZeRO-3: parameters themselves sharded over the data axis.

The invariant mirrors test_dp_pp.py's: sharding is a placement choice —
the same global batch must produce the same losses and the same updated
params whether the weights live replicated or 1/d-sliced over "data"
(fp-reassociation tolerance only). On top of parity, these tests assert
the memory claim itself: after `fsdp_param_specs` placement, the large
leaves (and the adam moments born from them) really are data-sharded.

The reference has no training and no data parallelism at all
(readme.md:112; SURVEY §2 parallelism table) — this whole axis is
beyond-parity surface.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, make_mesh


@pytest.fixture(scope="module")
def setup():
    cfg = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=4,
                        n_embd=32)
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size, dtype=jnp.int32)
    apply_fn = gpt.make_apply(cfg)

    def loss_fn(p, batch):
        return train.next_token_loss(apply_fn, p, batch)

    return cfg, params, tokens, loss_fn


def _data_sharded_leaves(specs):
    flat = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    return sum(1 for s in flat if DATA_AXIS in tuple(s))


def test_specs_shard_every_divisible_leaf(setup):
    cfg, params, _, _ = setup
    mesh = make_mesh({DATA_AXIS: 4}, jax.devices()[:4])
    specs = train.fsdp_param_specs(params, mesh)
    flat = jax.tree_util.tree_leaves_with_path(params)
    spec_flat = jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))
    assert len(flat) == len(spec_flat)
    for (path, leaf), spec in zip(flat, spec_flat):
        divisible = any(d % 4 == 0 and d >= 4 for d in leaf.shape)
        if divisible:
            assert DATA_AXIS in tuple(spec), (path, leaf.shape, spec)
        else:
            assert spec == P(), (path, leaf.shape, spec)


def test_fsdp_train_parity_and_sharding(setup):
    """3 adamw steps: FSDP run == replicated run (loss + final params),
    and the params/moments actually live 1/d-sliced."""
    cfg, params, tokens, loss_fn = setup
    opt = optax.adamw(1e-3)

    # reference: plain replicated single-program step
    ref_step = train.make_train_step(loss_fn, opt)
    p_ref, s_ref = params, opt.init(params)
    ref_losses = []
    for _ in range(3):
        p_ref, s_ref, loss = ref_step(p_ref, s_ref, tokens)
        ref_losses.append(float(loss))

    mesh = make_mesh({DATA_AXIS: 4}, jax.devices()[:4])
    specs = train.fsdp_param_specs(params, mesh)
    assert _data_sharded_leaves(specs) > 0
    p_sh = train.shard_pytree(params, mesh, specs)
    s_sh = jax.jit(opt.init)(p_sh)  # moments inherit the 1/d shardings
    step = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    got_losses = []
    for _ in range(3):
        p_sh, s_sh, loss = step(p_sh, s_sh, tokens)
        got_losses.append(float(loss))

    np.testing.assert_allclose(got_losses, ref_losses, rtol=1e-5)
    for (path, a), b in zip(jax.tree_util.tree_leaves_with_path(p_ref),
                            jax.tree.leaves(p_sh)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=2e-4, atol=1e-5, err_msg=str(path))

    # the memory claim: large param leaves and their adam moments are
    # physically data-sharded (addressable shard < full leaf)
    wte = p_sh["wte"]["embedding"]
    assert DATA_AXIS in tuple(wte.sharding.spec), wte.sharding
    shard_shape = wte.addressable_shards[0].data.shape
    assert np.prod(shard_shape) == np.prod(wte.shape) // 4, (
        shard_shape, wte.shape)
    mu_wte = s_sh[0].mu["wte"]["embedding"]
    assert DATA_AXIS in tuple(mu_wte.sharding.spec), mu_wte.sharding


def test_fsdp_composes_with_tp(setup):
    """2D weight sharding {data, model}: tp specs keep their axis, the
    data axis lands on a remaining free dim; loss parity vs replicated."""
    cfg, params, tokens, loss_fn = setup
    opt = optax.sgd(1e-2)
    mesh = make_mesh({DATA_AXIS: 2, MODEL_AXIS: 2}, jax.devices()[:4])
    tp = train.gpt_tp_specs(params)
    specs = train.fsdp_param_specs(params, mesh, base_specs=tp)
    # qkv kernel: tp on out-features, fsdp on in-features
    qkv_spec = specs["h_0"]["attn"]["qkv"]["kernel"]
    assert tuple(qkv_spec) == (DATA_AXIS, MODEL_AXIS), qkv_spec

    p_sh = train.shard_pytree(params, mesh, specs)
    step = train.make_sharded_train_step(loss_fn, opt, mesh, specs)
    p1, _, loss = step(p_sh, jax.jit(opt.init)(p_sh), tokens)

    ref_step = train.make_train_step(loss_fn, opt)
    p1_ref, _, loss_ref = ref_step(params, opt.init(params), tokens)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1_ref), jax.tree.leaves(p1)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_spec_idempotent(setup):
    """Applying fsdp_param_specs twice must not double-insert the axis."""
    cfg, params, _, _ = setup
    mesh = make_mesh({DATA_AXIS: 4}, jax.devices()[:4])
    once = train.fsdp_param_specs(params, mesh)
    twice = train.fsdp_param_specs(params, mesh, base_specs=once)
    assert jax.tree.map(tuple, once, is_leaf=lambda x: isinstance(x, P)) == \
        jax.tree.map(tuple, twice, is_leaf=lambda x: isinstance(x, P))
