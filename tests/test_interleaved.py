"""Interleaved (virtual-stage) pipeline schedule tests.

Invariants: the interleaved dataflow is a pure reordering — forward output
must equal the full model bit-for-close, training loss must match the
GPipe and 1F1B schedules on the same batch, and the schedule-length
arithmetic (the whole point: bubble (S-1)/(VM+S-1) instead of
(S-1)/(M+S-1)) must hold exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import train
from dnn_tpu.models import gpt
from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
from dnn_tpu.parallel.pipeline import (
    interleaved_schedule_steps,
    spmd_pipeline_interleaved,
)

CFG = gpt.GPTConfig(block_size=64, vocab_size=128, n_layer=8, n_head=4,
                    n_embd=32)


def _setup(n_stages, seed=0):
    params = gpt.init(jax.random.PRNGKey(seed), CFG)
    mesh = make_mesh({STAGE_AXIS: n_stages}, jax.devices()[:n_stages])
    stacked = gpt.stack_blocks(params, range(CFG.n_layer))  # (L, ...) chunks
    aux = {k: v for k, v in params.items() if not k.startswith("h_")}
    return params, mesh, stacked, aux


def test_schedule_step_arithmetic():
    # V=1 degrades to the GPipe length; V>1 shaves (V-1)(S-1) sub-step
    # equivalents off V*(M + S - 1)
    assert interleaved_schedule_steps(4, 1, 8) == 8 + 3
    assert interleaved_schedule_steps(4, 2, 8) == 2 * 8 + 3
    s, v, m = 4, 2, 8
    gpipe_equiv = v * (m + s - 1)
    assert gpipe_equiv - interleaved_schedule_steps(s, v, m) == (v - 1) * (s - 1)
    # relative bubble shrinks with V
    bubble = lambda steps, work: (steps - work) / steps
    b1 = bubble(interleaved_schedule_steps(s, 1, m), m)
    b2 = bubble(interleaved_schedule_steps(s, 2, m), 2 * m)
    assert b2 < b1


@pytest.mark.parametrize("v", [2, 4])
def test_interleaved_forward_matches_full_model(v):
    n_stages = 2
    params, mesh, stacked, aux = _setup(n_stages)
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                             CFG.vocab_size, dtype=jnp.int32)
    x = gpt.embed(aux, ids, cfg=CFG)
    per_chunk = CFG.n_layer // (v * n_stages)
    chunks = jax.tree.map(
        lambda p: p.reshape(v * n_stages, per_chunk, *p.shape[1:]), stacked)
    h = spmd_pipeline_interleaved(
        lambda bp, a: gpt.blocks_scan(bp, a, cfg=CFG),
        chunks, x, mesh=mesh, num_microbatches=4, virtual_stages=v)
    logits = gpt.head(aux, h.astype(jnp.float32), cfg=CFG)
    want = gpt.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_interleaved_v1_equals_stacked_dataflow():
    """virtual_stages=1 must reproduce the plain stacked pipeline."""
    n_stages = 4
    params, mesh, stacked, aux = _setup(n_stages, seed=3)
    ids = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0,
                             CFG.vocab_size, dtype=jnp.int32)
    x = gpt.embed(aux, ids, cfg=CFG)
    per = CFG.n_layer // n_stages
    chunks = jax.tree.map(
        lambda p: p.reshape(n_stages, per, *p.shape[1:]), stacked)
    h = spmd_pipeline_interleaved(
        lambda bp, a: gpt.blocks_scan(bp, a, cfg=CFG),
        chunks, x, mesh=mesh, num_microbatches=4, virtual_stages=1)
    logits = gpt.head(aux, h.astype(jnp.float32), cfg=CFG)
    want = gpt.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_interleaved_train_loss_matches_gpipe_and_1f1b():
    n_stages, v = 2, 2
    params, mesh, stacked, aux = _setup(n_stages, seed=5)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    opt = optax.sgd(1e-3)
    per_stage = CFG.n_layer // n_stages
    stage_chunks = jax.tree.map(
        lambda p: p.reshape(n_stages, per_stage, *p.shape[1:]), stacked)
    per_chunk = CFG.n_layer // (v * n_stages)
    v_chunks = jax.tree.map(
        lambda p: p.reshape(v * n_stages, per_chunk, *p.shape[1:]), stacked)

    def mk(schedule, chunked, vs=1):
        return train.make_pipeline_train_step(
            lambda bp, h: gpt.blocks_scan(bp, h, cfg=CFG),
            lambda a, ids: gpt.embed(a, ids, cfg=CFG),
            lambda a, h: gpt.head(a, h.astype(jnp.float32), cfg=CFG),
            opt, mesh, num_microbatches=2, schedule=schedule,
            virtual_stages=vs,
        ), chunked

    losses = {}
    grads = {}
    for name, (step, chunked) in {
        "gpipe": mk("gpipe", stage_chunks),
        "1f1b": mk("1f1b", stage_chunks),
        "interleaved": mk("interleaved", v_chunks, v),
    }.items():
        st, ax, _, lval = step(
            chunked, aux, (opt.init(chunked), opt.init(aux)), tokens)
        losses[name] = float(lval)
        # compare aux (embed/head) grads via the updated aux params —
        # layout-independent across schedules
        grads[name] = np.asarray(ax["wpe"]["embedding"])
    assert losses["interleaved"] == pytest.approx(losses["gpipe"], rel=1e-5)
    assert losses["interleaved"] == pytest.approx(losses["1f1b"], rel=1e-5)
    np.testing.assert_allclose(grads["interleaved"], grads["gpipe"],
                               atol=1e-5, rtol=1e-4)


def test_interleaved_validation_errors():
    n_stages = 2
    _, mesh, stacked, aux = _setup(n_stages)
    ids = jnp.zeros((4, 8), jnp.int32)
    x = gpt.embed(aux, ids, cfg=CFG)
    chunks = jax.tree.map(
        lambda p: p.reshape(4, 2, *p.shape[1:]), stacked)
    with pytest.raises(ValueError, match="divide"):
        spmd_pipeline_interleaved(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=CFG),
            chunks, x, mesh=mesh, num_microbatches=1, virtual_stages=2)
    with pytest.raises(ValueError, match="leading axis"):
        spmd_pipeline_interleaved(
            lambda bp, a: gpt.blocks_scan(bp, a, cfg=CFG),
            chunks, x, mesh=mesh, num_microbatches=2, virtual_stages=4)
    with pytest.raises(ValueError, match="interleaved"):
        train.make_pipeline_train_step(
            lambda bp, h: h, lambda a, i: i, lambda a, h: h,
            optax.sgd(1e-3), mesh, schedule="interleaved", virtual_stages=1)
