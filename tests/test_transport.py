"""Pluggable inter-stage transport (comm/transport.py + wirecodec.py).

Covers the ISSUE-7 contract:
  * zero-copy wire codec golden round-trips against the real protobuf
    (wire-compat is byte-level, both directions);
  * activation parity PINNED across grpc | shm | device on the same
    2-stage engine (same jit programs -> bitwise-identical outputs);
  * the negotiation fallback matrix — device -> shm -> grpc — with
    fail-loud explicit misconfig and a flight event on silent fallback;
  * a REAL 2-process shm hop (subprocess stage server, parent client);
  * the streamed Relay path (non-nested acks, chunked oversized
    payloads) and the per-transport deadline budgets;
  * the device hop program's PRG001 audit (collective-consistent
    switch branches).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.comm import transport as tx
from dnn_tpu.comm import wire_pb2 as pb
from dnn_tpu.comm import wirecodec as wc
from dnn_tpu.config import TopologyConfig


# ----------------------------------------------------------------------
# wirecodec: byte-level wire compatibility with protobuf
# ----------------------------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float16", "int32", "int8"])
def test_wirecodec_request_golden_vs_protobuf(dtype):
    arr = (np.random.default_rng(0).standard_normal((3, 5)) * 10).astype(dtype)
    req = wc.TensorRequest(request_id="gen:32:tr=abc.def",
                           tensor=wc.make_tensor(arr))
    data = wc.serialize_request(req)
    # ours -> protobuf parses identically
    p = pb.TensorRequest.FromString(data)
    assert p.request_id == req.request_id
    assert list(p.tensor.shape) == list(req.tensor.shape)
    assert p.tensor.dtype == dtype
    assert bytes(p.tensor.tensor_data) == bytes(req.tensor.tensor_data)
    assert req.ByteSize() == p.ByteSize()
    # protobuf -> ours parses identically, zero-copy view out
    back = wc.parse_request(p.SerializeToString())
    v = wc.tensor_view(back.tensor)
    np.testing.assert_array_equal(v, arr)
    assert not v.flags.writeable  # a VIEW over the wire buffer, no copy


def test_wirecodec_response_golden_vs_protobuf():
    r = wc.TensorResponse(status="[n1] ok",
                          result_tensor=wc.make_tensor(np.ones((2, 2))))
    p = pb.TensorResponse.FromString(wc.serialize_response(r))
    assert p.status == r.status and p.HasField("result_tensor")
    assert p.ByteSize() == r.ByteSize()
    # absent optional field round-trips as absent
    r2 = wc.parse_response(pb.TensorResponse(status="err").SerializeToString())
    assert not r2.HasField("result_tensor") and r2.status == "err"
    # pb messages pass through our serializer unchanged
    assert wc.serialize_response(pb.TensorResponse(status="x")) == \
        pb.TensorResponse(status="x").SerializeToString()


def test_wirecodec_bfloat16_and_scalar():
    import ml_dtypes

    arr = np.arange(6, dtype=np.float32).astype(ml_dtypes.bfloat16).reshape(2, 3)
    t = wc.make_tensor(arr)
    back = wc.parse_request(wc.serialize_request(
        wc.TensorRequest(request_id="r", tensor=t)))
    np.testing.assert_array_equal(wc.tensor_view(back.tensor), arr)
    s = wc.make_tensor(np.float32(3.5))
    out = wc.tensor_view(wc.parse_request(wc.serialize_request(
        wc.TensorRequest(tensor=s))).tensor)
    assert out.shape == () and float(out) == 3.5


def test_wirecodec_copied_counter_counts_only_forced_copies():
    m = obs.metrics()
    assert m is not None
    m.clear()
    # contiguous hot path: zero copied bytes
    wc.make_tensor(np.arange(1024, dtype=np.float32))
    snap = m.snapshot()["counters"]
    assert not any("payload_bytes_copied" in k for k in snap)
    # non-contiguous input forces a materialization — counted
    wc.make_tensor(np.arange(64, dtype=np.float32)[::2])
    snap = m.snapshot()["counters"]
    copied = [v for k, v in snap.items() if "payload_bytes_copied" in k]
    assert copied and copied[0] == 32 * 4


def test_wirecodec_crc_mismatch_raises():
    from dnn_tpu.io.serialization import PayloadCorruptError
    from dnn_tpu.native import native_available

    if not native_available():
        pytest.skip("crc verification requires the native codec")
    t = wc.make_tensor(np.arange(8, dtype=np.float32))
    bad = wc.Tensor(bytes(t.tensor_data), t.shape, t.dtype, t.crc32c ^ 1)
    with pytest.raises(PayloadCorruptError):
        wc.tensor_view(bad)


# ----------------------------------------------------------------------
# negotiation matrix (unit level: ladder, proofs, fail-loud, flight)
# ----------------------------------------------------------------------

def test_negotiate_same_process_picks_device():
    neg = tx.negotiate_over(lambda sid, txt: tx.answer_hello(txt),
                            transport="auto", target="t")
    assert neg.name == "device" and neg.relay_ok
    neg.sender.close()


def _cross_process_answer(sid, txt):
    """Simulate a same-host peer in ANOTHER process: the proc token
    differs, so the device rung fails and the shm probe decides."""
    offer = json.loads(txt)
    offer["proc"] = "not-this-process"
    return tx.answer_hello(json.dumps(offer))


def test_negotiate_cross_process_same_host_picks_shm():
    neg = tx.negotiate_over(_cross_process_answer, transport="auto",
                            target="t")
    assert neg.name == "shm"
    neg.sender.close()


def test_negotiate_reference_peer_falls_back_to_grpc_with_flight_event():
    obs.flight.recorder().clear()
    neg = tx.negotiate_over(lambda sid, txt: "[node2] got msg 'x'",
                            transport="auto", target="ref:1")
    assert neg.name == "grpc"
    assert not neg.relay_ok  # reference peers have no Relay RPC
    evs = [e for e in obs.flight.recorder().events()
           if e["kind"] == "transport_fallback"]
    assert evs and evs[-1]["target"] == "ref:1"


def test_negotiate_dnn_decline_keeps_relay_capability():
    """A dnn_tpu peer on another HOST declines device/shm but still
    advertises the streamed Relay RPC — the non-nested schedule
    survives on the grpc rung."""
    def cross_host(sid, txt):
        offer = json.loads(txt)
        offer["proc"] = "other"
        offer.pop("shm_probe", None)  # unreachable segment = other host
        return tx.answer_hello(json.dumps(offer))

    neg = tx.negotiate_over(cross_host, transport="auto", target="t")
    assert neg.name == "grpc" and neg.relay_ok


def test_explicit_misconfig_fails_loud():
    with pytest.raises(tx.TransportMisconfigError):
        tx.negotiate_over(lambda sid, txt: "[ref] got msg", transport="device")
    with pytest.raises(tx.TransportMisconfigError):
        tx.negotiate_over(lambda sid, txt: tx.decline_hello("nope"),
                          transport="shm")


def test_shm_probe_nonce_is_verified():
    """The shm rung must be PROVEN by the attach+nonce echo, not
    assumed: a peer that cannot read the probe segment's nonce is
    refused the rung."""
    def wrong_nonce(sid, txt):
        offer = json.loads(txt)
        offer["proc"] = "other"
        offer["shm_probe"] = "dnn_tpu_probe_nonexistent"
        return tx.answer_hello(json.dumps(offer))

    neg = tx.negotiate_over(wrong_nonce, transport="auto")
    assert neg.name == "grpc"


def test_hello_is_wire_compatible_json_over_sendmessage():
    """The handshake rides the reference's own SendMessage: the offer
    must be plain JSON text a reference server would log-and-echo
    without effect."""
    offer, probe = tx.build_offer("auto")
    try:
        parsed = json.loads(json.dumps(offer))
        assert parsed["v"] == 1 and "want" in parsed
    finally:
        tx.close_probe(probe)


# ----------------------------------------------------------------------
# deadline budgets follow the transport
# ----------------------------------------------------------------------

def test_hop_budget_grpc_matches_reference_arithmetic():
    from dnn_tpu.comm.client import pipeline_budget
    from dnn_tpu.comm.service import PER_STAGE_BUDGET_S

    assert tx.hop_budget_s("grpc", 1) == PER_STAGE_BUDGET_S == 30.0
    assert tx.hop_budget_s("grpc", 3) == 3 * PER_STAGE_BUDGET_S
    # grpc never shrinks warm: budget arithmetic is part of the
    # reference-compatible contract
    assert tx.hop_budget_s("grpc", 2, warm=True) == \
        tx.hop_budget_s("grpc", 2)
    assert pipeline_budget(2) == PER_STAGE_BUDGET_S * 2 + 30.0


def test_hop_budget_device_hop_sheds_the_grpc_margin():
    from dnn_tpu.comm.client import pipeline_budget

    # a WARM device/shm hop must not inherit the 30 s gRPC slice
    assert tx.hop_budget_s("device", 1, warm=True) < \
        tx.hop_budget_s("grpc", 1) / 5
    assert tx.hop_budget_s("shm", 1, warm=True) < \
        tx.hop_budget_s("grpc", 1) / 2
    # cold hops keep the compile-inclusive compute slice
    assert tx.hop_budget_s("device", 1) > 20.0
    assert pipeline_budget(4, transport="device") < pipeline_budget(4)


# ----------------------------------------------------------------------
# chunked relay framing
# ----------------------------------------------------------------------

def test_split_and_reassemble_chunks_roundtrip():
    big = np.random.default_rng(1).standard_normal(
        (600, 1024)).astype(np.float32)  # ~2.4 MB > CHUNK_BYTES
    req = wc.TensorRequest(request_id="r7", tensor=wc.make_tensor(big))
    frames = tx.split_requests(req, seq=3)
    assert len(frames) == -(-big.nbytes // tx.CHUNK_BYTES)
    asm = tx.ChunkAssembler()
    done = None
    for f in frames:
        # full wire round-trip per frame
        done = asm.add(wc.parse_request(wc.serialize_request(f)))
    assert done is not None
    base, seq, tensor = done
    assert base == "r7" and seq == 3
    np.testing.assert_array_equal(wc.tensor_view(tensor), big)


def test_small_payload_rides_one_frame_with_seq_tag():
    req = wc.TensorRequest(request_id="r1",
                           tensor=wc.make_tensor(np.zeros(4, np.float32)))
    frames = tx.split_requests(req, seq=5)
    assert len(frames) == 1
    base, seq, chunk = tx.parse_seq(frames[0].request_id)
    assert base == "r1" and seq == 5 and chunk is None


def test_out_of_order_chunk_fails_loud():
    big = np.zeros(900 * 1024, np.float32)  # 3.6 MB -> >= 3 chunks
    frames = tx.split_requests(
        wc.TensorRequest(request_id="r", tensor=wc.make_tensor(big)), 0)
    assert len(frames) >= 3
    asm = tx.ChunkAssembler()
    asm.add(frames[0])
    with pytest.raises(tx.TransportError):
        asm.add(frames[2])  # skipped frame 1


def test_ack_result_status_roundtrip():
    assert tx.parse_ack(tx.ack_status(7)) == 7
    assert tx.parse_ack("[n1] ok") is None
    seq, human = tx.parse_result(tx.result_status(3, "[n2] ok: 1"))
    assert seq == 3 and human == "[n2] ok: 1"
    seq, human = tx.parse_result("[n1] plain")
    assert seq is None and human == "[n1] plain"


# ----------------------------------------------------------------------
# the device hop program: PRG001-consistent compiled send/recv
# ----------------------------------------------------------------------

def test_hop_program_moves_rows_stage_to_stage():
    import jax
    from jax.sharding import Mesh

    from dnn_tpu.parallel.mesh import STAGE_AXIS

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), (STAGE_AXIS,))
    hop = tx.make_hop_program(mesh, STAGE_AXIS)
    buf = np.stack([np.full((2, 3), i, np.float32) for i in range(4)])
    out = np.asarray(hop(np.int32(0), buf))
    # hop 0: row 0 lands on stage 1; non-participating ranks read zeros
    np.testing.assert_array_equal(out[1], buf[0])
    assert (out[0] == 0).all() and (out[2] == 0).all()
    out2 = np.asarray(hop(np.int32(2), buf))
    np.testing.assert_array_equal(out2[3], buf[2])


def test_transport_program_audit_is_clean():
    from dnn_tpu.analysis.program import audit_transport_programs

    report = audit_transport_programs()
    assert report.get("findings") == []
    # one ppermute per hop branch, every branch identical (PRG001)
    assert set(report["collective_signature"]) == {"ppermute"}
    assert len(report["collective_signature"]) == report["stages"] - 1


# ----------------------------------------------------------------------
# parity across transports on a real 2-stage engine (in-process servers)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def two_stage():
    from dnn_tpu.comm.service import start_stage_server_in_background
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict({
        "nodes": [
            {"id": "node1", "address": "127.0.0.1:59451", "part_index": 0},
            {"id": "node2", "address": "127.0.0.1:59452", "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
        "device_type": "cpu",
    })
    engine = PipelineEngine(cfg)
    t1, stop1 = start_stage_server_in_background(engine, "node1")
    t2, stop2 = start_stage_server_in_background(engine, "node2")
    yield cfg, engine
    stop1()
    stop2()


def _client(cfg, transport):
    from dnn_tpu.comm.client import NodeClient

    return NodeClient(cfg.node_by_id("node1").address, transport=transport)


def test_parity_pinned_across_grpc_shm_device(two_stage):
    """The SAME activation through the same 2-stage engine over all
    three transports: outputs must be BITWISE identical (same jit
    programs, same devices — the transport moves bytes, it must not
    touch them)."""
    cfg, engine = two_stage
    x = np.asarray(engine.spec.example_input(batch_size=1))
    expect = np.asarray(engine.run(x))
    outs = {}
    for name in ("grpc", "shm", "device"):
        c = _client(cfg, "auto" if name == "device" else name)
        try:
            status, result = c.send_tensor(x, request_id=f"parity_{name}")
            assert c._negotiated.name == name
            assert result is not None
            outs[name] = np.asarray(result)
        finally:
            c.close()
    np.testing.assert_allclose(outs["grpc"], expect, atol=1e-5, rtol=1e-5)
    np.testing.assert_array_equal(outs["grpc"], outs["shm"])
    np.testing.assert_array_equal(outs["grpc"], outs["device"])


def test_relay_stream_parity_and_acks(two_stage):
    """The streamed (non-nested) path returns the same results as the
    unary chain, for every microbatch, in order."""
    cfg, engine = two_stage
    x = np.asarray(engine.spec.example_input(batch_size=1))
    expect = np.asarray(engine.run(x))
    c = _client(cfg, "auto")
    try:
        outs = c.send_tensors([x] * 5, request_id="relay_parity")
        assert len(outs) == 5
        for status, result in outs:
            assert "Prediction" in status
            np.testing.assert_allclose(result, expect, atol=1e-5, rtol=1e-5)
    finally:
        c.close()


def test_relay_stream_chunked_big_batch(two_stage):
    """An oversized microbatch (> CHUNK_BYTES) rides the stream in
    chunks and reassembles exactly — the unary path's 4 MB gRPC ceiling
    does not apply."""
    cfg, engine = two_stage
    x = np.asarray(engine.spec.example_input(batch_size=128))  # ~1.5 MB
    assert x.nbytes > tx.CHUNK_BYTES
    expect = np.asarray(engine.run(x))
    c = _client(cfg, "grpc")  # force inline payloads so chunking engages
    try:
        outs = c.send_tensors([x], request_id="relay_chunked")
        np.testing.assert_allclose(outs[0][1], expect, atol=1e-4, rtol=1e-4)
    finally:
        c.close()


def test_transport_labels_on_obs_series(two_stage):
    """Every hop's histogram/series carries the transport label (the
    fleet collector reads the PR's effect off these)."""
    cfg, engine = two_stage
    m = obs.metrics()
    assert m is not None
    x = np.asarray(engine.spec.example_input(batch_size=1))
    for name in ("grpc", "auto"):
        c = _client(cfg, name)
        try:
            c.send_tensor(x, request_id=f"lbl_{name}")
        finally:
            c.close()
    snap = m.snapshot()
    hists = snap.get("histogram", {})
    assert any("comm.rpc_latency_seconds" in k and 'transport="grpc"' in k
               for k in hists)
    assert any("comm.rpc_latency_seconds" in k and 'transport="device"' in k
               for k in hists)
    lats = snap.get("latency", {})
    assert any(k.startswith("comm.hop_seconds") and 'transport="device"' in k
               for k in lats)


def test_explicit_device_client_fails_loud_against_other_process():
    """--transport device against a peer that cannot prove same-process
    must ERROR, not silently degrade (negotiation runs against a fake
    cross-process answer)."""
    with pytest.raises(tx.TransportMisconfigError):
        tx.negotiate_over(_cross_process_answer, transport="device")


# ----------------------------------------------------------------------
# REAL 2-process shm hop: subprocess stage server, parent client
# ----------------------------------------------------------------------

_CHILD_SRC = """
import asyncio, sys
from dnn_tpu.config import TopologyConfig
from dnn_tpu.runtime.engine import PipelineEngine
from dnn_tpu.comm.service import serve_stage

cfg = TopologyConfig.from_dict({
    "nodes": [{"id": "n1", "address": "127.0.0.1:%d", "part_index": 0}],
    "num_parts": 1, "model": "cifar_cnn", "runtime": "relay",
    "device_type": "cpu",
})
engine = PipelineEngine(cfg)
asyncio.run(serve_stage(engine, "n1"))
"""


@pytest.mark.timeout(180)
def test_real_two_process_shm_hop(tmp_path):
    """The shm rung end-to-end across REAL process boundaries: a
    subprocess hosts the stage, the parent negotiates auto and must
    land on shm (device refused: different process; shm proven: same
    host), and the result matches the parent's local compute (same
    seed => same random init)."""
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.engine import PipelineEngine

    port = 59471
    script = tmp_path / "shm_stage_child.py"
    script.write_text(_CHILD_SRC % port)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    env.pop("XLA_FLAGS", None)  # the child needs no virtual mesh
    child = subprocess.Popen([sys.executable, str(script)], env=env,
                             stdout=subprocess.PIPE,
                             stderr=subprocess.STDOUT)

    def _up(deadline: float) -> bool:
        # poll with a FRESH channel per attempt: a sync gRPC channel
        # whose first connects fail while the child is still importing
        # can wedge in backoff inside this (jax-initialized, many-
        # threaded) pytest process and never notice the late bind —
        # observed on this host; a fresh channel sees the server
        # immediately. The production late-start path is covered by
        # test_comm's retry test (sub-second delay, same channel).
        t_end = time.monotonic() + deadline
        while time.monotonic() < t_end:
            probe = NodeClient(f"127.0.0.1:{port}")
            try:
                if probe.health_check(timeout=2.0):
                    return True
            finally:
                probe.close()
            time.sleep(1.0)
        return False

    c = None
    try:
        if not _up(120.0):
            child.terminate()
            out, _ = child.communicate(timeout=10)
            pytest.fail("child server never came up; child output:\n"
                        + out.decode(errors="replace")[-2000:])
        c = NodeClient(f"127.0.0.1:{port}")
        cfg = TopologyConfig.from_dict({
            "nodes": [{"id": "n1", "address": f"127.0.0.1:{port}",
                       "part_index": 0}],
            "num_parts": 1, "model": "cifar_cnn", "runtime": "relay",
            "device_type": "cpu",
        })
        local = PipelineEngine(cfg)
        x = np.asarray(local.spec.example_input(batch_size=1))
        status, result = c.send_tensor(x, request_id="shm_2proc")
        assert c._negotiated.name == "shm", (
            f"expected the shm rung across processes, got "
            f"{c._negotiated.name} ({c._negotiated.reason})")
        np.testing.assert_allclose(result, np.asarray(local.run(x)),
                                   atol=1e-5, rtol=1e-5)
        # second send reuses the ring slot (release-on-response)
        status2, result2 = c.send_tensor(x, request_id="shm_2proc_b")
        np.testing.assert_array_equal(result2, result)
        # streamed relay longer than the shm ring (4 slots): the
        # sender's writer thread must block on the ring and resume as
        # the peer's acks release slots — the backpressure cycle that
        # deadlocked when ring waits ran on an event loop (see
        # StageServer._forward_one)
        outs = c.send_tensors([x] * 6, request_id="shm_2proc_stream")
        assert len(outs) == 6
        for _st, r_i in outs:
            np.testing.assert_array_equal(r_i, result)
    finally:
        if c is not None:
            c.close()
        child.terminate()
        try:
            child.wait(timeout=10)
        except subprocess.TimeoutExpired:
            child.kill()
