"""dnn_tpu.chaos + self-healing serving (ISSUE 8).

Covers the injection side (deterministic seeded schedules, the seams)
and every recovery behavior it forces: supervised restart with backoff
and a crash-loop cap, request requeue on worker death (token parity vs
an uninterrupted run), connection draining under load (nothing lost,
nothing newly admitted), the client circuit breaker's
open/half-open/close cycle plus the fresh-channel rebuild, deadline
propagation plumbing, exactly-once admission dedup, and
corrupted-checkpoint restore that fails loud then falls back to the
previous good artifact.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import grpc
import jax
import numpy as np
import pytest

from dnn_tpu import chaos
from dnn_tpu.chaos import inject as chaos_inject
from dnn_tpu.chaos.plan import Fault, FaultPlan, decide
from dnn_tpu.comm import transport as tx
from dnn_tpu.io.serialization import PayloadCorruptError
from dnn_tpu.models import gpt
from dnn_tpu.obs import flight
from dnn_tpu.runtime.lm_server import (
    DrainingError,
    LMServer,
    _BatcherWorker,
    parse_gen_options,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    return gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(seed), CFG), CFG)


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Every test leaves the process injector-free — chaos is
    process-global state."""
    yield
    chaos_inject.uninstall()


# ----------------------------------------------------------------------
# plan + injector determinism
# ----------------------------------------------------------------------

def test_fault_plan_parse_and_validation(tmp_path):
    plan = FaultPlan.from_json(json.dumps({
        "seed": 3,
        "faults": [
            {"kind": "kill_stage", "target": "node2", "at_s": 5},
            {"kind": "rpc_drop", "seam": "client", "p": 0.5, "count": 2},
        ]}))
    assert plan.seed == 3
    assert [f.kind for f in plan.process_faults()] == ["kill_stage"]
    assert [f.kind for f in plan.inprocess_faults()] == ["rpc_drop"]
    # file form + the --chaos CLI dual (path or inline)
    p = tmp_path / "plan.json"
    p.write_text(json.dumps(plan.to_dict()))
    assert FaultPlan.from_cli(str(p)).to_dict() == plan.to_dict()
    assert FaultPlan.from_cli(json.dumps(plan.to_dict())).seed == 3
    # a typo'd plan fails LOUD — silently injecting nothing would "pass"
    # every chaos assertion
    with pytest.raises(ValueError, match="unknown fault kind"):
        Fault(kind="kill_stge")
    with pytest.raises(ValueError, match="unknown fault fields"):
        FaultPlan.from_dict({"faults": [{"kind": "rpc_drop", "pp": 1}]})
    with pytest.raises(ValueError):
        FaultPlan.from_cli("/nonexistent/plan.json")


def test_injection_schedule_deterministic_golden():
    """Same plan + seed -> bit-identical decision sequence, across
    injector instances (the replay contract: no wall-clock randomness
    in any consulted seam)."""
    plan = FaultPlan.from_dict({
        "seed": 7,
        "faults": [{"kind": "rpc_drop", "seam": "client", "p": 0.5,
                    "count": 3}]})

    def decisions(inj):
        out = []
        for _ in range(10):
            try:
                inj.perturb_rpc("client", "t:1")
                out.append(".")
            except grpc.RpcError:
                out.append("D")
        return "".join(out)

    a = decisions(chaos_inject.Injector(plan))
    b = decisions(chaos_inject.Injector(plan))
    assert a == b
    # GOLDEN for seed 7: blake2s is stable across platforms/runs, so
    # this exact firing pattern (3 drops, budget-capped) is pinned
    assert a == ".D.D..D..."
    # pure decision function is stable
    assert decide(7, "a", 1) == decide(7, "a", 1)
    assert decide(7, "a", 1) != decide(7, "a", 2)


def test_rpc_and_relay_seams():
    chaos.install({"seed": 0, "faults": [
        {"kind": "rpc_corrupt", "seam": "stage", "p": 1.0, "count": 1},
        {"kind": "rpc_delay", "seam": "stage", "p": 1.0, "count": 1,
         "delay_s": 0.01},
        {"kind": "relay_drop", "p": 1.0, "count": 1},
        {"kind": "relay_corrupt", "p": 1.0, "count": 1},
        {"kind": "kv_exhaust", "from_n": 0, "count": 2},
    ]})
    # corrupt fires first (listed first), then delay, then nothing
    with pytest.raises(PayloadCorruptError, match="chaos"):
        chaos_inject.perturb_rpc("stage", "x")
    chaos_inject.perturb_rpc("stage", "x")  # delay: sleeps, no raise
    chaos_inject.perturb_rpc("stage", "x")  # budgets exhausted
    # relay seam: drop -> frame vanishes (assembler returns None)
    from dnn_tpu.comm import wirecodec as wc

    asm = tx.ChunkAssembler()
    req = wc.TensorRequest(request_id=tx.tag_seq("r", 0),
                           tensor=wc.make_tensor(np.arange(4.0)))
    assert asm.add(req) is None           # relay_drop ate it
    with pytest.raises(PayloadCorruptError):   # relay_corrupt
        asm.add(req)
    out = asm.add(req)                    # budgets exhausted: delivers
    assert out is not None and out[1] == 0
    # kv seam: two scheduled exhaustions then clear
    assert chaos_inject.kv_exhaust() is True
    assert chaos_inject.kv_exhaust() is True
    assert chaos_inject.kv_exhaust() is False
    # every firing left a flight event
    kinds = [e["fault"] for e in flight.recorder().events(
        kind="chaos_inject")]
    for k in ("rpc_corrupt", "rpc_delay", "relay_drop", "relay_corrupt",
              "kv_exhaust"):
        assert k in kinds
    # uninstalled: all seams are no-ops
    chaos_inject.uninstall()
    chaos_inject.perturb_rpc("stage", "x")
    assert chaos_inject.perturb_relay() is False
    assert chaos_inject.kv_exhaust() is False


def test_train_fault_seam_golden():
    # the training-loop seam (ISSUE 19): exact step counters, a
    # DIRECTIVE dict instead of a raise — train.fit executes it inside
    # its data window so the injected cost lands where the fault claims
    chaos.install({"seed": 0, "faults": [
        {"kind": "train_fault", "target": "sleep", "at_n": 1,
         "count": 2, "delay_s": 0.25},
        {"kind": "train_fault", "target": "nan", "at_n": 4, "count": 1},
    ]})
    try:
        got = [chaos_inject.train_fault() for _ in range(6)]
    finally:
        chaos_inject.uninstall()
    # GOLDEN firing pattern over the seeded "train" counter: n=0 clear,
    # n∈[1,3) sleep with the configured delay, n=3 clear, n=4 nan
    assert got[0] is None and got[3] is None and got[5] is None
    assert got[1] == {"mode": "sleep", "delay_s": 0.25} == got[2]
    assert got[4]["mode"] == "nan"
    # every firing left a flight event carrying its mode
    fired = [(e["n"], e["mode"]) for e in flight.recorder().events(
        kind="chaos_inject") if e.get("fault") == "train_fault"]
    assert fired[-3:] == [(1, "sleep"), (2, "sleep"), (4, "nan")]
    # uninstalled: the seam is one None check
    assert chaos_inject.train_fault() is None


def test_poison_batch_floats_only():
    # the nan directive's executor: float leaves drown, int leaves
    # (token batches) pass through untouched — the documented contract
    # that forces the sentinel probe onto a float toy model
    from dnn_tpu.train import poison_batch

    batch = {"tokens": np.arange(6, dtype=np.int32).reshape(2, 3),
             "x": np.ones((2, 2), dtype=np.float32)}
    out = poison_batch(batch)
    assert np.isnan(out["x"]).all()
    assert out["tokens"].dtype == np.int32
    assert (out["tokens"] == batch["tokens"]).all()


def test_deadline_propagation_plumbing():
    rid = tx.tag_deadline("gen:8:tr=ab.cd", 12.5)
    assert tx.extract_deadline(rid) == 12.5
    assert tx.strip_deadline(rid) == "gen:8:tr=ab.cd"
    # re-tagging replaces, never stacks
    rid2 = tx.tag_deadline(rid, 3.0)
    assert rid2.count("dl=") == 1 and tx.extract_deadline(rid2) == 3.0
    # the LM daemon's option parser skips dl= (wire-compat: transport
    # metadata, not a generation option) and parses d= as the dedup key
    max_new, seed, opts = parse_gen_options(tx.tag_deadline("gen:8", 5),
                                            32)
    assert (max_new, seed) == (8, None) and "dl" not in str(opts)
    _, _, opts = parse_gen_options("gen:4:d=key1", 32)
    assert opts["dedup"] == "key1"
    # reference rids pass through untouched
    assert tx.extract_deadline("req:1234") is None
    assert tx.strip_deadline("req:1234") == "req:1234"


# ----------------------------------------------------------------------
# watchdog: injected wedge + escalation hook
# ----------------------------------------------------------------------

def test_watchdog_injected_wedge_and_escalation():
    from dnn_tpu.obs.watchdog import Watchdog

    fired = []
    wd = Watchdog(period_s=0.1, probe_deadline_s=0.5,
                  device_probe=lambda d: (True, "stub ok"),
                  on_wedged=fired.append)
    inj = chaos.install({"seed": 0, "faults": []})
    wd.start()
    try:
        time.sleep(0.35)
        assert wd.state() == "ok"
        inj.activate_wedge()
        t0 = time.monotonic()
        while wd.state() != "wedged" and time.monotonic() - t0 < 5:
            time.sleep(0.05)
        st = wd.status()
        assert st["state"] == "wedged"
        assert "chaos" in st["components"]["device"]["detail"]
        # escalation fired ONCE per episode, not once per probe round
        time.sleep(0.5)
        assert len(fired) == 1 and "chaos" in fired[0]
        # recovery re-arms the latch; a second episode fires again
        inj.clear_wedge()
        t0 = time.monotonic()
        while wd.state() != "ok" and time.monotonic() - t0 < 5:
            time.sleep(0.05)
        assert wd.state() == "ok"
        inj.activate_wedge()
        t0 = time.monotonic()
        while len(fired) < 2 and time.monotonic() - t0 < 5:
            time.sleep(0.05)
        assert len(fired) == 2
        # the injection itself is in the ring (reconstructable incident)
        assert any(e["fault"] == "wedge_device"
                   for e in flight.recorder().events(kind="chaos_inject"))
    finally:
        wd.close()


# ----------------------------------------------------------------------
# supervisor
# ----------------------------------------------------------------------

def test_supervisor_restart_backoff_and_crash_loop():
    from dnn_tpu.chaos.supervisor import Supervisor

    # a child that dies instantly: restarts walk the backoff ladder and
    # the crash-loop cap gives up instead of kill-9ing forever
    sup = Supervisor(
        lambda: subprocess.Popen([sys.executable, "-c",
                                  "raise SystemExit(3)"]),
        name="crashy", backoff_s=0.05, backoff_max_s=0.4,
        health_interval_s=0.05, crash_loop_max=3,
        crash_loop_window_s=60.0, stable_after_s=60.0)
    sup.start()
    t0 = time.monotonic()
    while sup.state != "crashloop" and time.monotonic() - t0 < 30:
        time.sleep(0.05)
    sup.stop()
    assert sup.state in ("crashloop", "stopped")
    assert sup.restarts == 3
    backoffs = [e for e in flight.recorder().events(
        kind="supervisor_backoff") if e["stage"] == "crashy"]
    assert len(backoffs) >= 3
    # exponential: each recorded delay doubles (0.05, 0.1, 0.2, ...)
    delays = [e["delay_s"] for e in backoffs[:3]]
    assert delays == [0.05, 0.1, 0.2]
    assert any(e["stage"] == "crashy" for e in flight.recorder().events(
        kind="crash_loop"))


def test_supervisor_recovers_killed_child():
    from dnn_tpu.chaos.supervisor import Supervisor

    sup = Supervisor(
        lambda: subprocess.Popen([sys.executable, "-c",
                                  "import time; time.sleep(120)"]),
        name="healthy", backoff_s=0.05, health_interval_s=0.05)
    sup.start()
    try:
        time.sleep(0.3)
        sup.inject_kill()
        t0 = time.monotonic()
        while sup.restarts < 1 and time.monotonic() - t0 < 20:
            time.sleep(0.05)
        assert sup.restarts == 1
        assert any(e["stage"] == "healthy" for e in
                   flight.recorder().events(kind="supervisor_restart"))
        # the replacement is a live, different process
        time.sleep(0.2)
        assert sup.proc.poll() is None
    finally:
        sup.stop()


def test_corrupted_checkpoint_restore_fails_loud_then_falls_back(
        tmp_path):
    from dnn_tpu.chaos.supervisor import restore_latest_good
    from dnn_tpu.io.train_ckpt import save_train_state

    state1 = {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}
    state2 = {"w": np.full((2, 3), 7.0, dtype=np.float32)}
    ckpt_dir = str(tmp_path / "ckpts")
    save_train_state(ckpt_dir, 1, state1)
    p2 = save_train_state(ckpt_dir, 2, state2)
    chaos.corrupt_file(p2, seed=1)
    like = {"w": np.zeros((2, 3), np.float32)}
    state, step, path = restore_latest_good(ckpt_dir, like)
    assert step == 1 and path.endswith("step_00000001.npz")
    np.testing.assert_array_equal(np.asarray(state["w"]), state1["w"])
    # the failure is LOUD in the ring, and the fallback is recorded
    fails = flight.recorder().events(kind="ckpt_restore_failed")
    assert any(e["path"].endswith("step_00000002.npz") for e in fails)
    assert any(e["step"] == 1 for e in
               flight.recorder().events(kind="ckpt_restore_recovered"))
    # nothing loadable -> explicit error naming the failures
    chaos.corrupt_file(os.path.join(ckpt_dir, "step_00000001.npz"),
                       seed=2)
    with pytest.raises(RuntimeError, match="no loadable checkpoint"):
        restore_latest_good(ckpt_dir, like)


# ----------------------------------------------------------------------
# LM server: requeue on worker death
# ----------------------------------------------------------------------

def test_requeue_on_worker_death_token_parity():
    """An injected device-step fault kills the batcher worker mid-run;
    the requeue path restarts the worker and resubmits — final tokens
    equal an uninterrupted run of the same seeded requests."""
    srv = LMServer(CFG, _prepared(), slots=2, max_len=32, prompt_pad=8,
                   default_max_new=6, worker_restarts=2)
    try:
        prompts = [np.array([1, 2, 3], np.int32),
                   np.array([4, 5], np.int32)]
        # baseline: uninterrupted (no injector installed)
        base = [srv.worker.submit(p, 6, 100 + i).result(timeout=120)
                for i, p in enumerate(prompts)]
        first_worker = srv.worker
        # now kill the NEXT device step; the requeued rerun must match
        chaos.install({"seed": 0, "faults": [
            {"kind": "step_fault", "at_n": 0, "count": 1}]})
        futs = [srv.worker.submit(p, 6, 100 + i)
                for i, p in enumerate(prompts)]
        out = [f.result(timeout=120) for f in futs]
        for got, want in zip(out, base):
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want))
        assert srv.worker is not first_worker, "worker was not replaced"
        assert not first_worker.is_alive()
        assert srv.worker.is_alive()
        ring = flight.recorder()
        assert any(e.get("requeue") for e in ring.events(
            kind="worker_died"))
        restarts = ring.events(kind="worker_restart")
        assert restarts and restarts[-1]["requeued"] >= 1
    finally:
        chaos_inject.uninstall()
        srv.close()


def test_requeue_budget_exhausted_fails_fast():
    """A fault that kills EVERY step exhausts the restart budget and
    degrades to the pre-ISSUE-8 fail-fast shape (bounded, visible) —
    never a requeue loop."""
    srv = LMServer(CFG, _prepared(seed=1), slots=2, max_len=32,
                   prompt_pad=8, worker_restarts=1)
    try:
        chaos.install({"seed": 0, "faults": [
            {"kind": "step_fault", "at_n": 0, "count": 10_000}]})
        fut = srv.worker.submit(np.array([1, 2, 3], np.int32), 4, 7)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="worker died"):
            fut.result(timeout=60)
        assert time.monotonic() - t0 < 30
        assert flight.recorder().events(kind="worker_restart_exhausted")
    finally:
        chaos_inject.uninstall()
        srv.close()


# ----------------------------------------------------------------------
# draining
# ----------------------------------------------------------------------

def test_drain_under_load_no_loss_no_new_admits():
    srv = ContinuousBatcher(CFG, _prepared(seed=2), slots=1, max_len=64,
                            prompt_pad=8)
    worker = _BatcherWorker(srv)
    worker.start()
    # slots=1: the first request decodes while the others queue
    in_flight = worker.submit(np.array([1, 2, 3], np.int32), 24, 1)
    queued = [worker.submit(np.array([4, 5], np.int32), 8, 2),
              worker.submit(np.array([6], np.int32), 8, 3)]
    worker.begin_drain()
    # the admitted request FINISHES (its caller paid for the decode)
    assert in_flight.result(timeout=120).shape == (24,)
    # queued work hands back RETRIABLE — never silently lost
    for f in queued:
        with pytest.raises(DrainingError, match="retry against"):
            f.result(timeout=30)
    # no new admissions once draining
    late = worker.submit(np.array([7], np.int32), 4, 4)
    with pytest.raises(DrainingError):
        late.result(timeout=5)
    worker.join(timeout=30)
    assert not worker.is_alive()
    ring = flight.recorder()
    assert ring.events(kind="drain_begin")
    assert ring.events(kind="drain_done")
    assert any(e["requests"] >= 2 for e in ring.events(
        kind="drain_handback"))


def test_drainz_http_endpoint_and_healthz():
    srv = LMServer(CFG, _prepared(seed=3), slots=1, max_len=32,
                   prompt_pad=8, metrics_port=0, drain_grace_s=30.0)
    try:
        port = srv.metrics_server.port
        base = f"http://127.0.0.1:{port}"
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
        req = urllib.request.Request(base + "/drainz", method="POST",
                                     data=b"")
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 202
            body = json.loads(r.read())
            assert body["draining"] is True
        # idempotent second POST
        with urllib.request.urlopen(req, timeout=5) as r:
            assert json.loads(r.read())["draining"] is True
        # readiness flips: healthz 503 while draining/drained
        t0 = time.monotonic()
        code = 200
        while time.monotonic() - t0 < 10:
            try:
                with urllib.request.urlopen(base + "/healthz",
                                            timeout=5) as r:
                    code = r.status
            except urllib.error.HTTPError as e:
                code = e.code
            if code == 503:
                break
            time.sleep(0.1)
        assert code == 503
        # drain completes: worker exits, escalation latch set (the
        # serve_lm loop would exit now), statusz carries the drain
        # component while the watchdog-less fallback applies
        assert srv._escalated.wait(timeout=30)
        st = srv._statusz()
        assert st["components"]["drain"]["state"] == "draining"
    finally:
        srv.close()


def test_preflight_rejects_unavailable_while_draining():
    """Over the wire: a draining daemon answers UNAVAILABLE (the
    retriable status the client ladder honors) and HealthCheck goes
    unhealthy — the hand-back contract end to end."""
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    port = 59315
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=4), port=port, slots=2, max_len=32,
        prompt_pad=8, default_max_new=4)
    try:
        c = NodeClient(f"127.0.0.1:{port}", breaker=False)
        assert c.generate(np.array([1, 2], np.int32),
                          max_new_tokens=3).shape == (3,)
        stop.servicer._drainz()
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError) as ei:
            # retries=0: surface the first status, no ladder
            c.send_tensor(np.array([1, 2], np.int32),
                          request_id="gen:3", timeout=10, retries=0)
        assert ei.value.code() == grpc.StatusCode.UNAVAILABLE
        assert "drain" in (ei.value.details() or "").lower()
        assert time.monotonic() - t0 < 5
        assert not c.health_check()
        c.close()
    finally:
        stop()


# ----------------------------------------------------------------------
# exactly-once dedup at admission
# ----------------------------------------------------------------------

def test_dedup_joins_identical_key_over_grpc():
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    port = 59316
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=5), port=port, slots=2, max_len=32,
        prompt_pad=8, default_max_new=4)
    try:
        c = NodeClient(f"127.0.0.1:{port}", breaker=False)
        p = np.array([1, 2, 3], np.int32)
        a = c.generate(p, max_new_tokens=4, seed=10, dedup="k1")
        # same dedup key, DIFFERENT seed: a non-deduped server would
        # generate a different stream — the join returns the original
        b = c.generate(p, max_new_tokens=4, seed=999, dedup="k1")
        np.testing.assert_array_equal(a, b)
        # a different key generates independently
        d = c.generate(p, max_new_tokens=4, seed=999, dedup="k2")
        assert not np.array_equal(a, d) or True  # streams may collide;
        # the CONTRACT is the join event below, not inequality
        joins = flight.recorder().events(kind="dedup_join")
        assert any(e["key"] == "k1" for e in joins)
        assert not any(e["key"] == "k2" for e in joins)
        # review regression: a STREAMING request carrying a d= key must
        # serve (the key is dropped — streams can't join), never reach
        # batcher.submit as an unknown kwarg
        toks = list(c.generate_stream(p, max_new_tokens=3, seed=1,
                                      dedup="k3"))
        assert len(toks) == 3
        c.close()
    finally:
        stop()


# ----------------------------------------------------------------------
# circuit breaker + channel rebuild
# ----------------------------------------------------------------------

def test_circuit_breaker_open_half_open_close_cycle():
    from dnn_tpu.comm.client import CircuitBreaker

    b = CircuitBreaker("t:1", threshold=2, cooldown_s=0.15,
                       max_cooldown_s=1.0)
    assert b.allow() and b.state == "closed"
    b.record(False)
    b.record(False)
    assert b.state == "open" and not b.allow()
    time.sleep(0.2)
    assert b.allow() and b.state == "half_open"  # ONE probe
    assert not b.allow()                         # second probe blocked
    # review regression: a DELEGATED call releases the probe slot
    # instead of judging it — the next allow() re-issues it instantly
    # (an unsettled half_open slot would shed traffic forever)
    b.release()
    assert b.state == "open" and b.allow() and b.state == "half_open"
    b.record(False)                              # probe failed
    assert b.state == "open"
    assert b._cooldown == pytest.approx(0.3)     # doubled
    time.sleep(0.35)
    assert b.allow()
    b.record(True)
    assert b.state == "closed" and b.allow()
    assert b._cooldown == pytest.approx(0.15)    # reset
    kinds = [e["kind"] for e in flight.recorder().events()
             if e.get("target") == "t:1"]
    for k in ("circuit_open", "circuit_half_open", "circuit_reopen",
              "circuit_close"):
        assert k in kinds


def test_client_sheds_fast_when_open_and_rebuilds_channel():
    from dnn_tpu.comm.client import CircuitBreaker, CircuitOpenError, \
        NodeClient

    # nothing listens here: every call is a connect failure
    c = NodeClient("127.0.0.1:59399",
                   breaker=CircuitBreaker("127.0.0.1:59399", threshold=2,
                                          cooldown_s=5.0))
    x = np.arange(4.0)
    for _ in range(2):
        with pytest.raises(grpc.RpcError):
            c.send_tensor(x, request_id="r", timeout=2.0, retries=0)
    # breaker open: fail is O(1), no connect timeout paid
    t0 = time.monotonic()
    with pytest.raises(CircuitOpenError):
        c.send_tensor(x, request_id="r", timeout=2.0, retries=0)
    assert time.monotonic() - t0 < 0.2
    # the two consecutive UNAVAILABLEs also crossed the rebuild
    # threshold: the wedged-backoff channel was replaced (PR 7 lesson,
    # fixed in the client proper)
    assert c.channel_rebuilds >= 1
    assert any(e["target"] == "127.0.0.1:59399" for e in
               flight.recorder().events(kind="channel_rebuild"))
    c.close()


def test_wait_healthy_rides_channel_rebuild_to_late_server():
    """The PR 7 stale-channel scenario, solved inside the client: a
    server that binds AFTER the first failed connects is still found by
    the same NodeClient instance (no fresh-client workaround)."""
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    port = 59317
    c = NodeClient(f"127.0.0.1:{port}", breaker=False)
    # burn a few failed probes first — the old behavior parked the
    # channel in reconnect backoff here
    for _ in range(3):
        assert not c.health_check(timeout=0.5)
    assert c.channel_rebuilds >= 1
    t, stop = start_lm_server_in_background(
        CFG, _prepared(seed=6), port=port, slots=1, max_len=32,
        prompt_pad=8)
    try:
        assert c.wait_healthy(deadline=30.0, interval=0.3)
        c.close()
    finally:
        stop()
