"""Observability layer tests (dnn_tpu/obs + the grown utils/metrics).

The acceptance contract this module pins (ISSUE 3): one end-to-end
generate request through LMServer produces (a) a valid Chrome-trace JSON
with nested queue/prefill/decode/RPC spans sharing ONE trace id, and
(b) a /metrics scrape containing TTFT, inter-token quantiles, batch
occupancy, per-stage RPC latency, and a nonzero jax_compilations_total —
plus the unit contracts underneath: span-tree nesting, wire-tag
propagation across an in-process client->stage hop, Prometheus golden
output, empty-reservoir snapshots, windowed throughput, the compile
listener firing under jax.jit, and the `python -m dnn_tpu.obs trace
--selftest` smoke the CI path invokes."""

import json
import subprocess
import sys
import threading
import urllib.request

import numpy as np
import pytest

from dnn_tpu import obs
from dnn_tpu.utils.metrics import (
    Histogram,
    LatencyReservoir,
    Metrics,
    Throughput,
    labeled,
    render_prometheus,
)


# ----------------------------------------------------------------------
# metrics primitives (satellite: utils/metrics.py sharp edges)
# ----------------------------------------------------------------------

def test_empty_reservoir_snapshot_is_safe():
    r = LatencyReservoir()
    assert r.quantiles() == {}  # no ValueError on an empty reservoir
    m = Metrics()
    m.latencies["nothing_yet"] = LatencyReservoir()
    snap = m.snapshot()  # must not raise
    assert snap["latency"]["nothing_yet"] == {"count": 0}
    json.loads(m.json_line())


def test_throughput_is_really_windowed():
    clock = iter([0.0, 1.0, 2.0, 3.0, 100.0, 100.0, 130.0]).__next__
    t = Throughput(window_s=60.0, now=clock)  # created at t=0
    t.add(30)   # t=1
    t.add(30)   # t=2
    # t=3: 60 items over 3 s of lifetime (pre-warmup under-report, never
    # an event-span spike)
    assert t.per_sec == pytest.approx(20.0)
    # t=100: everything older than t=40 evicted -> rate decays to zero
    # (the cumulative-since-first-add implementation reported ~0.6 here)
    assert t.per_sec == 0.0
    t.add(60)   # t=100
    # t=130: 60 items over the full 60 s wall window — a burst after an
    # idle gap must NOT divide by its own ~0 event span (the ~1e9 gauge
    # spike the wall-window denominator exists to prevent)
    assert t.per_sec == pytest.approx(1.0)


def test_histogram_buckets_cumulative():
    h = Histogram(buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["buckets"] == {0.01: 1, 0.1: 3, 1.0: 3}
    assert snap["count"] == 4
    assert snap["sum"] == pytest.approx(5.105)


def test_callable_gauge_is_fresh_at_render():
    m = Metrics()
    vals = iter([1.0, 2.0])
    m.set_fn("fresh_gauge", lambda: next(vals))
    assert "fresh_gauge 1" in render_prometheus(m)
    assert "fresh_gauge 2" in render_prometheus(m)  # re-evaluated
    m.set_fn("dying_gauge", lambda: 1 / 0)
    assert m.snapshot()["gauges"]["dying_gauge"] == 0.0  # never breaks


def test_bulk_updates_and_gauge_fn_reregistration():
    m = Metrics()
    m.bulk(counters={"c_total": 2}, gauges={"g": 1.5},
           observations={"lat": [0.1, 0.2]},
           gauge_fns={"fn_g": lambda: 42})
    snap = m.snapshot()
    assert snap["counters"]["c_total"] == 2
    assert snap["gauges"]["g"] == 1.5
    assert snap["gauges"]["fn_g"] == 42
    assert snap["latency"]["lat"]["count"] == 2
    m.clear()
    assert "fn_g" not in m.snapshot()["gauges"]
    m.bulk(gauge_fns={"fn_g": lambda: 7})  # every bulk re-registers, so
    assert m.snapshot()["gauges"]["fn_g"] == 7  # a clear() self-heals


def test_labeled_canonical_and_escaped():
    assert labeled("x_total") == "x_total"
    assert labeled("x_total", b="2", a="1") == 'x_total{a="1",b="2"}'
    assert labeled("x", k='say "hi"') == r'x{k="say \"hi\""}'


def test_prometheus_golden_output():
    m = Metrics()
    m.inc("requests_total", 3)
    m.inc(labeled("comm.retries_total", stage="node1"))
    m.set("serving.batch_occupancy", 0.5)
    m.observe("lat_seconds", 0.01)
    m.observe("lat_seconds", 0.03)
    m.observe_hist("h_seconds", 0.05, buckets=(0.01, 0.1))
    assert render_prometheus(m) == (
        "# TYPE comm_retries_total counter\n"
        'comm_retries_total{stage="node1"} 1\n'
        "# TYPE h_seconds histogram\n"
        'h_seconds_bucket{le="0.01"} 0\n'
        'h_seconds_bucket{le="0.1"} 1\n'
        'h_seconds_bucket{le="+Inf"} 1\n'
        "h_seconds_sum 0.05\n"
        "h_seconds_count 1\n"
        "# TYPE lat_seconds summary\n"
        'lat_seconds{quantile="0.5"} 0.01\n'
        'lat_seconds{quantile="0.9"} 0.03\n'
        'lat_seconds{quantile="0.99"} 0.03\n'
        "lat_seconds_sum 0.04\n"
        "lat_seconds_count 2\n"
        "# TYPE requests_total counter\n"
        "requests_total 3\n"
        "# TYPE serving_batch_occupancy gauge\n"
        "serving_batch_occupancy 0.5\n"
    )


# ----------------------------------------------------------------------
# span trees + wire propagation
# ----------------------------------------------------------------------

def test_span_tree_nesting_and_cross_thread_parent():
    with obs.span("root", kind="test") as root:
        with obs.span("child_a"):
            with obs.span("grandchild"):
                pass

        def worker():
            s = obs.start_span("child_b", parent=root)
            s.end(tokens=2)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    by_name = {s.name: s for s in obs.collector().spans(root.trace_id)}
    assert set(by_name) == {"root", "child_a", "grandchild", "child_b"}
    assert by_name["root"].parent_id is None
    assert by_name["child_a"].parent_id == by_name["root"].span_id
    assert by_name["grandchild"].parent_id == by_name["child_a"].span_id
    assert by_name["child_b"].parent_id == by_name["root"].span_id
    assert by_name["child_b"].attrs["tokens"] == 2
    assert all(s.dur >= 0 for s in by_name.values())


def test_wire_tag_roundtrip_and_option_parser_immunity():
    from dnn_tpu.runtime.lm_server import parse_gen_options

    root = obs.start_span("req")
    rid = obs.tag_request_id("gen:12:7", root)
    root.end()
    assert obs.parse_wire_tag(rid) == (root.trace_id, root.span_id)
    assert obs.strip_wire_tag(rid) == "gen:12:7"
    # the tag must be invisible to the option parser (wire compat)
    assert parse_gen_options(rid, 32) == (12, 7, {})
    # untagged ids parse to None, and tagging is a no-op when off
    assert obs.parse_wire_tag("gen:12") is None
    assert obs.tag_request_id("gen:12", obs.NULL_SPAN) == "gen:12"


def test_disabled_gate_is_free_and_restores():
    obs.set_enabled(False)
    try:
        assert obs.metrics() is None
        s = obs.start_span("nope")
        assert s is obs.NULL_SPAN and not s
        s.child("x").end()
        with obs.span("nope2") as s2:
            assert s2 is None
    finally:
        obs.set_enabled(True)
    assert obs.metrics() is not None


def test_chrome_trace_schema():
    with obs.span("outer", a=1) as root:
        with obs.span("inner"):
            pass
    ct = obs.collector().chrome_trace(root.trace_id)
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner"}
    for e in xs:
        assert e["ts"] > 0 and e["dur"] >= 0 and e["pid"] == 1
        assert e["args"]["trace_id"] == root.trace_id
    # JSONL export round-trips through the CLI converter schema
    lines = [json.loads(ln)
             for ln in obs.collector().jsonl(root.trace_id).splitlines()]
    assert len(lines) == 2
    assert {"trace_id", "span_id", "parent_id", "name", "ts", "dur",
            "tid", "attrs"} <= set(lines[0])


def test_trace_cli_selftest_smoke():
    # the tier-1 smoke invocation the CI path mandates (ISSUE satellite)
    out = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "trace", "--selftest"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "obs selftest ok" in out.stdout


def test_trace_cli_jsonl_to_chrome(tmp_path):
    with obs.span("convertme") as root:
        pass
    src = tmp_path / "spans.jsonl"
    dst = tmp_path / "chrome.json"
    obs.collector().dump_jsonl(str(src), root.trace_id)
    out = subprocess.run(
        [sys.executable, "-m", "dnn_tpu.obs", "trace", "--jsonl", str(src),
         "--out", str(dst), "--id", root.trace_id],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    ct = json.loads(dst.read_text())
    assert [e["name"] for e in ct["traceEvents"]
            if e["ph"] == "X"] == ["convertme"]


# ----------------------------------------------------------------------
# /metrics endpoint + compile telemetry
# ----------------------------------------------------------------------

def test_metrics_http_endpoint_scrape():
    from dnn_tpu.obs.http import MetricsHTTPServer

    reg = Metrics()
    reg.inc("scrape_me_total", 7)
    col = obs.TraceCollector()
    srv = MetricsHTTPServer(port=0, host="127.0.0.1", registry=reg,
                            collector=col, healthy=lambda: True)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "# TYPE scrape_me_total counter" in body
        assert "scrape_me_total 7" in body
        assert urllib.request.urlopen(base + "/healthz").status == 200
        ct = json.loads(urllib.request.urlopen(base + "/trace").read())
        assert ct["traceEvents"] == []
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope")
    finally:
        srv.close()


def test_compile_counter_fires_under_jit():
    import jax
    import jax.numpy as jnp

    assert obs.install_compile_telemetry()
    m = obs.metrics()
    before = m.counters.get("jax_compilations_total", 0)
    before_s = m.counters.get("jax_compile_seconds_total", 0.0)

    @jax.jit
    def f(x):
        return x * 3 + 1

    f(jnp.ones((7,))).block_until_ready()
    assert m.counters["jax_compilations_total"] > before
    assert m.counters["jax_compile_seconds_total"] > before_s
    # cache hit: no new compile counted
    mid = m.counters["jax_compilations_total"]
    f(jnp.ones((7,))).block_until_ready()
    assert m.counters["jax_compilations_total"] == mid


# ----------------------------------------------------------------------
# batcher instrumentation (direct, no gRPC)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_gpt():
    import jax

    from dnn_tpu.models import gpt

    cfg = gpt.GPTConfig(block_size=64, vocab_size=64, n_layer=2, n_head=2,
                        n_embd=32)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


def test_batcher_bucket_spans_and_metrics(tiny_gpt):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg, prepared = tiny_gpt
    m = obs.metrics()
    srv = ContinuousBatcher(cfg, prepared, slots=2, max_len=48,
                            prompt_pad=16, decode_buckets=(16, 32, 48))
    root = obs.start_span("request")
    srv.submit(np.arange(1, 9), max_new_tokens=30, trace=root)
    srv.drain()
    root.end()
    spans = obs.collector().spans(root.trace_id)
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    assert set(by_name) == {"request", "admit", "prefill", "decode"}
    # admit under request, prefill under admit
    assert by_name["admit"][0].parent_id == root.span_id
    assert by_name["prefill"][0].parent_id == by_name["admit"][0].span_id
    # per-BUCKET decode spans: the request decodes through 16 -> 32 -> 48
    buckets = sorted(s.attrs["bucket"] for s in by_name["decode"])
    assert buckets == [16, 32, 48]
    last = max(by_name["decode"], key=lambda s: s.attrs["bucket"])
    assert last.attrs["reason"] == "length"
    assert last.attrs["tokens"] == 30
    # counters: dispatch per bucket + grows + retirement outcome
    assert m.counters[labeled("serving.decode_bucket_dispatch_total",
                              bucket=16)] >= 1
    assert m.counters[labeled("serving.decode_bucket_dispatch_total",
                              bucket=48)] >= 1
    assert m.counters["serving.decode_bucket_grow_total"] >= 2
    assert m.counters[labeled("serving.requests_total",
                              outcome="length")] >= 1
    assert m.latencies["serving.inter_token_seconds"].count >= 29


def test_batcher_gauges_do_not_pin_dead_pools(tiny_gpt):
    import gc
    import weakref

    from dnn_tpu.runtime.serving import ContinuousBatcher
    from dnn_tpu.utils.metrics import default_metrics

    cfg, prepared = tiny_gpt
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=32,
                            prompt_pad=16)
    srv.submit(np.arange(1, 5), 4)
    srv.drain()  # registers the weakly-bound callable gauges
    wr = weakref.ref(srv)
    del srv
    gc.collect()
    # the registry's gauge callables must not keep the pool (and its KV
    # cache) alive; a collected pool's gauges read 0 at scrape
    assert wr() is None
    assert default_metrics.snapshot()["gauges"][
        "serving.batch_occupancy"] == 0.0


def test_batcher_untraced_requests_make_no_spans(tiny_gpt):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg, prepared = tiny_gpt
    srv = ContinuousBatcher(cfg, prepared, slots=1, max_len=32,
                            prompt_pad=16)
    n_before = len(obs.collector().spans())
    srv.submit(np.arange(1, 5), max_new_tokens=4)
    srv.drain()
    assert len(obs.collector().spans()) == n_before


# ----------------------------------------------------------------------
# end-to-end: client -> stage hop trace propagation
# ----------------------------------------------------------------------

def test_stage_hop_trace_propagation():
    from dnn_tpu.comm.client import NodeClient
    from dnn_tpu.comm.service import start_stage_server_in_background
    from dnn_tpu.config import TopologyConfig
    from dnn_tpu.runtime.engine import PipelineEngine

    cfg = TopologyConfig.from_dict({
        "nodes": [
            {"id": "node1", "address": "127.0.0.1:59361", "part_index": 0},
            {"id": "node2", "address": "127.0.0.1:59362", "part_index": 1},
        ],
        "num_parts": 2, "model": "cifar_cnn", "runtime": "relay",
    })
    engine = PipelineEngine(cfg)
    t1, stop1 = start_stage_server_in_background(engine, "node1")
    t2, stop2 = start_stage_server_in_background(engine, "node2")
    try:
        x = np.asarray(engine.spec.example_input(batch_size=1))
        c = NodeClient(cfg.node_by_id("node1").address)
        with obs.span("client.request") as root:
            status, result = c.send_tensor(x, request_id="trace_hop_1")
        c.close()
    finally:
        stop1()
        stop2()
    assert result is not None
    spans = obs.collector().spans(root.trace_id)
    names = sorted(s.name for s in spans)
    # client RPC span + per-hop forward span + both stages' request and
    # compute spans — ONE trace id across three "processes"
    assert names == sorted(["client.request", "rpc.SendTensor",
                            "stage.request", "stage.compute",
                            "rpc.forward", "stage.request",
                            "stage.compute"])
    stages = {s.attrs["stage"] for s in spans if s.name == "stage.request"}
    assert stages == {"node1", "node2"}
    # parent chain crosses the wire: node1's stage.request hangs under
    # the client's rpc span; node2's under node1's forward span
    by_id = {s.span_id: s for s in spans}
    for s in spans:
        if s.name == "stage.request" and s.attrs["stage"] == "node2":
            assert by_id[s.parent_id].name == "rpc.forward"
        if s.name == "rpc.forward":
            assert by_id[s.parent_id].name == "stage.request"


# ----------------------------------------------------------------------
# end-to-end acceptance: generate through LMServer -> trace + scrape
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def lm_obs_server(tiny_gpt):
    from dnn_tpu.runtime.lm_server import start_lm_server_in_background

    cfg, prepared = tiny_gpt
    t, stop = start_lm_server_in_background(
        cfg, prepared, port=59461, slots=2, max_len=64, prompt_pad=16,
        default_max_new=8, metrics_port=0)
    yield stop.servicer
    stop()


def test_e2e_generate_trace_and_metrics_scrape(lm_obs_server):
    from dnn_tpu.comm.client import NodeClient

    c = NodeClient("127.0.0.1:59461")
    with obs.span("client.generate") as root:
        toks = c.generate([1, 2, 3, 4], max_new_tokens=10, seed=0)
    c.close()
    assert len(toks) == 10

    # (a) one trace id, nested queue/prefill/decode/RPC spans
    spans = obs.collector().spans(root.trace_id)
    by_name = {s.name: s for s in spans}
    assert {"client.generate", "rpc.SendTensor", "lm.request",
            "queue_wait", "admit", "prefill", "decode"} <= set(by_name)
    assert by_name["lm.request"].parent_id == \
        by_name["rpc.SendTensor"].span_id
    assert by_name["queue_wait"].parent_id == \
        by_name["lm.request"].span_id
    assert by_name["prefill"].parent_id == by_name["admit"].span_id
    assert by_name["decode"].attrs["tokens"] == 10
    assert by_name["lm.request"].attrs["tokens"] == 10
    ct = obs.collector().chrome_trace(root.trace_id)
    xs = [e for e in ct["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == len(spans)
    assert {e["args"]["trace_id"] for e in xs} == {root.trace_id}

    # (b) the /metrics scrape — served from the LMServer's own endpoint
    port = lm_obs_server.metrics_server.port
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{port}/metrics").read().decode()
    for needle in (
            'serving_ttft_seconds{quantile="0.5"}',
            'serving_inter_token_seconds{quantile="0.99"}',
            "serving_batch_occupancy",
            "serving_queue_wait_seconds_count",
            "serving_tokens_per_sec",
            "serving_kv_slot_utilization",
            "comm_rpc_latency_seconds_bucket",
            "serving_requests_total{",
    ):
        assert needle in body, f"missing {needle!r} in scrape"
    # nonzero compile counter: the daemon's own programs compiled under
    # the listener (installed before the batcher's first submit)
    line = next(ln for ln in body.splitlines()
                if ln.startswith("jax_compilations_total"))
    assert float(line.split()[-1]) > 0
    # the trace endpoint renders this very request's timeline
    ct2 = json.loads(urllib.request.urlopen(
        f"http://127.0.0.1:{port}/trace?id={root.trace_id}").read())
    assert any(e.get("name") == "decode" for e in ct2["traceEvents"])


def test_lm_server_releases_metrics_port_on_failed_construction(tiny_gpt):
    from dnn_tpu.obs.http import MetricsHTTPServer
    from dnn_tpu.runtime.lm_server import LMServer

    cfg, prepared = tiny_gpt
    with pytest.raises(ValueError):
        # invalid batcher kwargs AFTER the endpoint has bound: the
        # failed construction must release the port, or a retry hits
        # EADDRINUSE for the rest of the process
        LMServer(cfg, prepared, metrics_port=59477, slots=1, max_len=32,
                 prompt_pad=16, allow_constraints=True, constraint_rows=1)
    srv = MetricsHTTPServer(port=59477, host="127.0.0.1")  # rebinds
    srv.close()


def test_e2e_streaming_and_text_front_spans(lm_obs_server):
    from dnn_tpu.comm.client import NodeClient

    c = NodeClient("127.0.0.1:59461")
    with obs.span("client.stream") as root:
        toks = list(c.generate_stream([1, 2, 3], max_new_tokens=5, seed=1))
    c.close()
    assert len(toks) == 5
    by_name = {s.name: s for s in obs.collector().spans(root.trace_id)}
    assert {"client.stream", "rpc.GenerateStream", "lm.request",
            "decode"} <= set(by_name)
    assert by_name["rpc.GenerateStream"].attrs["tokens"] == 5
    assert by_name["lm.request"].attrs["method"] == "GenerateStream"
