"""Group-wise int4 weight-only quantization.

Contracts:
  * the group-batched apply (ops.nn._linear_int4) equals matmul against
    the explicitly dequantized kernel — the group decomposition is
    algebra, not approximation;
  * group-wise scales beat per-column scales on quantization error (the
    reason int4 needs groups at all);
  * the quantized tree drops into the standard forward/decode paths via
    the shared linear dispatch, at half int8's kernel bytes;
  * indivisible group sizes are rejected.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu import quant
from dnn_tpu.models import gpt
from dnn_tpu.ops.nn import linear

CFG = gpt.GPTConfig(block_size=48, vocab_size=128, n_layer=2, n_head=4,
                    n_embd=64)  # n_embd divisible by the test group sizes


def test_apply_equals_dequantized_matmul():
    w = jax.random.normal(jax.random.PRNGKey(0), (128, 96))
    b = jax.random.normal(jax.random.PRNGKey(1), (96,))
    x = jax.random.normal(jax.random.PRNGKey(2), (4, 128))
    q, scale = quant.quantize_tensor_int4(w, group=32)
    assert q.dtype == jnp.int4 and scale.shape == (4, 96)

    got = linear({"q": q, "scale": scale, "bias": b}, x)
    # dequantize explicitly: per-group scale broadcast over its 32 rows
    deq = (q.astype(jnp.float32).reshape(4, 32, 96)
           * scale[:, None, :]).reshape(128, 96)
    want = x @ deq + b
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_groupwise_beats_per_column():
    # heavy-tailed weights (outliers) are where groups matter
    w = jax.random.t(jax.random.PRNGKey(0), df=3.0, shape=(512, 64))

    def rms_err(q, scale, group):
        deq = (q.astype(jnp.float32).reshape(512 // group, group, 64)
               * scale[:, None, :]).reshape(512, 64)
        # RMS, not max: the worst-case group still contains the global
        # outlier, so max error cannot improve — groups win by giving
        # every OTHER group a tight scale
        return float(jnp.sqrt(jnp.mean((deq - w) ** 2)))

    q64, s64 = quant.quantize_tensor_int4(w, group=64)
    q512, s512 = quant.quantize_tensor_int4(w, group=512)  # == per-column
    # measured ~1.8x RMS improvement on df=3 tails; assert a solid margin
    assert rms_err(q64, s64, 64) < 0.7 * rms_err(q512, s512, 512)


def test_gpt_int4_forward_and_decode():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    prepared = gpt.prepare_stacked(params, CFG)
    q4 = quant.quantize_gpt(prepared, bits=4, int4_group=32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 9), 0,
                             CFG.vocab_size, dtype=jnp.int32)

    ref = gpt.make_apply_stacked(CFG)(prepared, ids)
    got = gpt.make_apply_stacked(CFG)(q4, ids)
    # int4 is lossy; the contract is "same prediction class", checked as
    # high logit correlation rather than closeness
    c = np.corrcoef(np.asarray(ref).ravel(), np.asarray(got).ravel())[0, 1]
    assert c > 0.98, c

    from dnn_tpu.runtime.generate import make_generate

    toks = make_generate(CFG, max_new_tokens=5)(
        q4, ids[:, :5], jax.random.PRNGKey(2))
    assert np.asarray(toks).shape == (2, 5)

    # bytes at REAL model dims (the toy model's 32-row groups carry ~12%
    # f32-scale overhead that blurs the ratio): a gpt2-small mlp.fc
    # kernel at the default group lands near the ideal 0.5625 bytes/wt
    # (0.5 int4 + 4/64 scale) vs int8's ~1.005
    w = jnp.zeros((768, 3072))
    q4k, s4k = quant.quantize_tensor_int4(w)
    q8k, s8k = quant.quantize_tensor(w)
    b4 = quant.param_bytes({"q": q4k, "scale": s4k})
    b8 = quant.param_bytes({"q": q8k, "scale": s8k})
    bf = quant.param_bytes({"kernel": w})
    assert b4 < 0.60 * b8, (b4, b8)
    assert b8 < 0.27 * bf, (b8, bf)


def test_indivisible_group_rejected():
    w = jnp.ones((100, 8))
    with pytest.raises(ValueError, match="not divisible"):
        quant.quantize_tensor_int4(w, group=64)


def test_stacked_scales_slice_with_scan():
    """Stacked (L, in, out) kernels quantize to (L, G, out) scales; the
    blocks scan slices both in lockstep (same contract as int8)."""
    w = jax.random.normal(jax.random.PRNGKey(0), (3, 128, 64))
    q, scale = quant.quantize_tensor_int4(w, group=32)
    assert q.shape == (3, 128, 64) and scale.shape == (3, 4, 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 128))
    for layer in range(3):
        got = linear({"q": q[layer], "scale": scale[layer]}, x)
        deq = (q[layer].astype(jnp.float32).reshape(4, 32, 64)
               * scale[layer][:, None, :]).reshape(128, 64)
        np.testing.assert_allclose(np.asarray(got), np.asarray(x @ deq),
                                   rtol=1e-5, atol=1e-5)
