"""MoE KV-cache generation + serving tests.

Round-2 gap being closed: gpt_moe could forward and train but not serve
(no decode path anywhere in dnn_tpu/runtime/). Oracles are the family's
own stateless forward (dense-routed, tests/test_gpt_moe.py pins that one
against the EP forward) — the reference has no MoE at all (SURVEY.md §2).

Routing caveat the tests encode: per-token top-k routing is batch-size
independent only when nothing is dropped for capacity, so decode-parity
tests use a generous capacity_factor (drops are batch-dependent in ANY
capacity-based MoE; prefill routes the same token set as the full
forward and needs no such allowance).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, gpt_moe
from dnn_tpu.parallel.mesh import EXPERT_AXIS
from dnn_tpu.runtime.generate import init_cache
from dnn_tpu.runtime.generate_moe import (
    forward_with_cache_moe,
    make_generate_moe,
    make_generate_moe_ep,
    moe_cache_ffn,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt_moe.PRESETS["gpt2-moe-test"]  # L=2, C=32, E=4, top_k=2, d_ff=64
# generous capacity: no token ever dropped -> routing is batch-independent
CFG_HI = dataclasses.replace(CFG, capacity_factor=8.0)


def _prepared(cfg, seed=0):
    params = gpt_moe.init(jax.random.PRNGKey(seed), cfg)
    return params, gpt.prepare_stacked(params, cfg)


def test_moe_prefill_logits_match_full_forward():
    params, prepared = _prepared(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab_size)
    cache = init_cache(CFG, 2, 16)
    logits_cache, cache = forward_with_cache_moe(prepared, ids, cache, 0, cfg=CFG)
    logits_full = gpt_moe.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_cache), np.asarray(logits_full), atol=2e-4)


def test_moe_incremental_decode_matches_full_recompute():
    params, prepared = _prepared(CFG_HI)
    apply_fn = gpt_moe.make_apply(CFG_HI)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG_HI.vocab_size)
    n_new = 6
    gen = make_generate_moe(CFG_HI, max_new_tokens=n_new, temperature=0.0)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_moe_ep_decode_matches_dense_grouped(devices):
    n = 2
    mesh = jax.sharding.Mesh(np.array(devices[:n]), (EXPERT_AXIS,))
    _, prepared = _prepared(CFG_HI, seed=3)
    ids = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0, CFG_HI.vocab_size)
    n_new = 5
    dense = make_generate_moe(CFG_HI, max_new_tokens=n_new, groups=n)
    ep = make_generate_moe_ep(CFG_HI, mesh, max_new_tokens=n_new)
    want = np.asarray(dense(prepared, ids, jax.random.PRNGKey(0)))
    got = np.asarray(ep(prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_moe_ep_rejects_bad_batch(devices):
    mesh = jax.sharding.Mesh(np.array(devices[:2]), (EXPERT_AXIS,))
    _, prepared = _prepared(CFG_HI)
    gen = make_generate_moe_ep(CFG_HI, mesh, max_new_tokens=2)
    with pytest.raises(ValueError):
        gen(prepared, jnp.zeros((3, 8), jnp.int32), jax.random.PRNGKey(0))


def test_moe_batcher_matches_solo_decode():
    """A greedy MoE slot in the pool == a solo batch-1 MoE run."""
    _, prepared = _prepared(CFG_HI, seed=5)
    prompts = [np.array([5, 3, 7, 1, 2]), np.array([9, 8, 2])]
    n_new = 6
    srv = ContinuousBatcher(
        CFG_HI, prepared, slots=2, max_len=32, prompt_pad=8,
        ffn=moe_cache_ffn(CFG_HI))
    rids = [srv.submit(p, max_new_tokens=n_new) for p in prompts]
    results = srv.drain()

    gen = make_generate_moe(CFG_HI, max_new_tokens=n_new, temperature=0.0)
    for rid, p in zip(rids, prompts):
        want = np.asarray(
            gen(prepared, jnp.asarray(p, jnp.int32)[None, :],
                jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[rid], want)
