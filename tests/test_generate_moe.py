"""MoE KV-cache generation + serving tests.

Round-2 gap being closed: gpt_moe could forward and train but not serve
(no decode path anywhere in dnn_tpu/runtime/). Oracles are the family's
own stateless forward (dense-routed, tests/test_gpt_moe.py pins that one
against the EP forward) — the reference has no MoE at all (SURVEY.md §2).

Routing caveat the tests encode: per-token top-k routing is batch-size
independent only when nothing is dropped for capacity, so decode-parity
tests use a generous capacity_factor (drops are batch-dependent in ANY
capacity-based MoE; prefill routes the same token set as the full
forward and needs no such allowance).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dnn_tpu.models import gpt, gpt_moe
from dnn_tpu.parallel.mesh import EXPERT_AXIS
from dnn_tpu.runtime.generate import init_cache
from dnn_tpu.runtime.generate_moe import (
    forward_with_cache_moe,
    make_generate_moe,
    make_generate_moe_ep,
    moe_cache_ffn,
)
from dnn_tpu.runtime.serving import ContinuousBatcher

CFG = gpt_moe.PRESETS["gpt2-moe-test"]  # L=2, C=32, E=4, top_k=2, d_ff=64
# generous capacity: no token ever dropped -> routing is batch-independent
CFG_HI = dataclasses.replace(CFG, capacity_factor=8.0)


def _prepared(cfg, seed=0):
    params = gpt_moe.init(jax.random.PRNGKey(seed), cfg)
    return params, gpt.prepare_stacked(params, cfg)


def test_moe_prefill_logits_match_full_forward():
    params, prepared = _prepared(CFG)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, CFG.vocab_size)
    cache = init_cache(CFG, 2, 16)
    logits_cache, cache = forward_with_cache_moe(prepared, ids, cache, 0, cfg=CFG)
    logits_full = gpt_moe.make_apply(CFG)(params, ids)
    np.testing.assert_allclose(
        np.asarray(logits_cache), np.asarray(logits_full), atol=2e-4)


def test_moe_incremental_decode_matches_full_recompute():
    params, prepared = _prepared(CFG_HI)
    apply_fn = gpt_moe.make_apply(CFG_HI)
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0, CFG_HI.vocab_size)
    n_new = 6
    gen = make_generate_moe(CFG_HI, max_new_tokens=n_new, temperature=0.0)
    got = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))

    cur = np.asarray(ids)
    want = []
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1)).astype(np.int32)
        want.append(nxt)
        cur = np.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.stack(want, axis=1))


def test_moe_ep_decode_matches_dense_grouped(devices):
    n = 2
    mesh = jax.sharding.Mesh(np.array(devices[:n]), (EXPERT_AXIS,))
    _, prepared = _prepared(CFG_HI, seed=3)
    ids = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0, CFG_HI.vocab_size)
    n_new = 5
    dense = make_generate_moe(CFG_HI, max_new_tokens=n_new, groups=n)
    ep = make_generate_moe_ep(CFG_HI, mesh, max_new_tokens=n_new)
    want = np.asarray(dense(prepared, ids, jax.random.PRNGKey(0)))
    got = np.asarray(ep(prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_moe_ep_rejects_bad_batch(devices):
    mesh = jax.sharding.Mesh(np.array(devices[:2]), (EXPERT_AXIS,))
    _, prepared = _prepared(CFG_HI)
    gen = make_generate_moe_ep(CFG_HI, mesh, max_new_tokens=2)
    with pytest.raises(ValueError):
        gen(prepared, jnp.zeros((3, 8), jnp.int32), jax.random.PRNGKey(0))


def test_moe_batcher_matches_solo_decode():
    """A greedy MoE slot in the pool == a solo batch-1 MoE run."""
    _, prepared = _prepared(CFG_HI, seed=5)
    prompts = [np.array([5, 3, 7, 1, 2]), np.array([9, 8, 2])]
    n_new = 6
    srv = ContinuousBatcher(
        CFG_HI, prepared, slots=2, max_len=32, prompt_pad=8,
        ffn=moe_cache_ffn(CFG_HI))
    rids = [srv.submit(p, max_new_tokens=n_new) for p in prompts]
    results = srv.drain()

    gen = make_generate_moe(CFG_HI, max_new_tokens=n_new, temperature=0.0)
    for rid, p in zip(rids, prompts):
        want = np.asarray(
            gen(prepared, jnp.asarray(p, jnp.int32)[None, :],
                jax.random.PRNGKey(0)))[0]
        np.testing.assert_array_equal(results[rid], want)


def test_moe_pipeline_decode_matches_dense(devices):
    """PP x dense-MoE: stage-sharded blocks (each stage carrying its
    layers' full expert sets), routed FFN inside the cached ring block."""
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked
    from dnn_tpu.runtime.generate_moe import make_pipeline_generate_moe

    _, prepared = _prepared(CFG_HI, seed=21)
    mesh = make_mesh({STAGE_AXIS: 2}, devices[:2])
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG_HI, mesh)
    ids = jax.random.randint(jax.random.PRNGKey(22), (2, 6), 0,
                             CFG_HI.vocab_size)
    gen = make_pipeline_generate_moe(CFG_HI, mesh, max_new_tokens=5)
    got = np.asarray(gen(stage_blocks, aux, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate_moe(CFG_HI, max_new_tokens=5)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_moe_speculative_greedy_parity():
    """Speculative decoding with an MoE TARGET (generous capacity so
    routing is chunk-size independent): greedy output == target-only
    decode, with a dense GPT-2 draft proposing (same vocab)."""
    from dnn_tpu.runtime.speculative import make_speculative_generate

    _, prepared = _prepared(CFG_HI, seed=23)
    ids = jax.random.randint(jax.random.PRNGKey(24), (1, 8), 0,
                             CFG_HI.vocab_size)
    n = 8
    want = np.asarray(make_generate_moe(CFG_HI, max_new_tokens=n)(
        prepared, ids, jax.random.PRNGKey(0)))

    g_cfg = gpt.PRESETS["gpt2-test"]  # vocab 256 matches
    g_prep = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(25), g_cfg),
                                 g_cfg)
    spec = make_speculative_generate(CFG_HI, g_cfg, max_new_tokens=n, k=3)
    got = np.asarray(spec(prepared, g_prep, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_moe_ep_pp_2d_decode_matches_dense(devices):
    """EP x PP: experts sharded WITHIN each pipeline stage over a 2D
    {stage, expert} mesh — the composition the dense-expert pipeline
    decoder leaves out. Greedy parity vs the dense-grouped decoder."""
    from dnn_tpu.parallel.mesh import STAGE_AXIS, make_mesh
    from dnn_tpu.runtime.generate import prepare_pipeline_stacked
    from dnn_tpu.runtime.generate_moe import make_pipeline_generate_moe_ep

    _, prepared = _prepared(CFG_HI, seed=31)
    mesh = make_mesh({STAGE_AXIS: 2, EXPERT_AXIS: 2}, devices[:4])
    # reuse the stage-major reshape; expert leaves get re-placed inside
    stage_mesh = make_mesh({STAGE_AXIS: 2}, devices[:2])
    stage_blocks, aux = prepare_pipeline_stacked(prepared, CFG_HI, stage_mesh)
    stage_blocks = jax.tree.map(np.asarray, stage_blocks)  # host copies

    ids = jax.random.randint(jax.random.PRNGKey(32), (4, 6), 0,
                             CFG_HI.vocab_size)
    gen = make_pipeline_generate_moe_ep(CFG_HI, mesh, max_new_tokens=5)
    got = np.asarray(gen(stage_blocks, aux, ids, jax.random.PRNGKey(0)))
    want = np.asarray(make_generate_moe(CFG_HI, max_new_tokens=5, groups=2)(
        prepared, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
