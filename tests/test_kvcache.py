"""int8 / bf16 KV-cache codec tests.

The cache codec (dnn_tpu/runtime/kvcache.py) must be numerically
transparent up to the storage rounding: per-row scales commute with both
attention einsums, so the ONLY error source is int8 rounding of each K/V
row. Bounds here: per-step logits cosine > 0.999 vs the f32 cache, and
bit-exact equality of the scale-commutation algebra on synthetic data."""

import jax
import jax.numpy as jnp
import numpy as np

from dnn_tpu.models import gpt
from dnn_tpu.runtime.generate import forward_with_cache, init_cache, make_generate
from dnn_tpu.runtime.kvcache import FloatKV, Int8KV, _quantize_rows, codec_for_cache

CFG = gpt.PRESETS["gpt2-test"]


def _prepared(seed=0):
    params = gpt.init(jax.random.PRNGKey(seed), CFG)
    return params, gpt.prepare_stacked(params, CFG)


def _cos(a, b):
    a = np.asarray(a, np.float64).ravel()
    b = np.asarray(b, np.float64).ravel()
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-30))


def test_quantize_rows_roundtrip():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 3, 64)) * 3.0
    q, s = _quantize_rows(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    deq = np.asarray(q, np.float32) * np.asarray(s)[..., None]
    assert _cos(deq, x) > 0.9999
    # max per-row error bounded by half a quantization step (plus f32
    # rounding slack in the scale division itself)
    step = np.asarray(s)[..., None] * 0.5001 + 1e-6
    assert (np.abs(deq - np.asarray(x)) <= step).all()
    # zero rows are exact (scale guard, no NaN)
    qz, sz = _quantize_rows(jnp.zeros((3, 5)))
    assert not np.isnan(np.asarray(sz)).any()
    assert (np.asarray(qz) == 0).all()


def test_codec_inference():
    assert isinstance(codec_for_cache(init_cache(CFG, 1, 8)), FloatKV)
    assert isinstance(codec_for_cache(init_cache(CFG, 1, 8, "int8")), Int8KV)
    c = init_cache(CFG, 2, 8, "int8")
    assert c["k"].dtype == jnp.int8 and c["ks"].dtype == jnp.float32


def test_scale_commutation_is_exact():
    """attend(q, int8 cache) must equal attention against the explicitly
    dequantized cache — the scales' commutation with the einsums is
    algebra, not approximation."""
    b, h, s, d = 2, 3, 16, 8
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d))
    q = jax.random.normal(jax.random.PRNGKey(3), (b, h, 1, d))
    codec = Int8KV()
    kq, ks = _quantize_rows(k)
    vq, vs = _quantize_rows(v)
    cache = {"k": kq, "v": vq, "ks": ks, "vs": vs}
    pos_limit = jnp.array([s - 1])
    got = codec.attend(q, cache, pos_limit)

    deq_k = kq.astype(jnp.float32) * ks[..., None]
    deq_v = vq.astype(jnp.float32) * vs[..., None]
    want = FloatKV().attend(q, {"k": deq_k, "v": deq_v}, pos_limit)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_int8_attend_rows_causal_matches_dequantized():
    """The codec protocol is uniform: Int8KV implements the per-row causal
    verify variant too (unreachable from SpeculativeBatcher, which pins
    float caches — this direct test is what keeps the method honest). The
    scale folding must equal FloatKV on the explicitly dequantized cache,
    exactly, for every row's causal limit."""
    b, h, s, d, t = 2, 3, 16, 8, 4
    k = jax.random.normal(jax.random.PRNGKey(1), (b, h, s, d))
    v = jax.random.normal(jax.random.PRNGKey(2), (b, h, s, d))
    q = jax.random.normal(jax.random.PRNGKey(3), (b, h, t, d))
    kq, ks = _quantize_rows(k)
    vq, vs = _quantize_rows(v)
    cache = {"k": kq, "v": vq, "ks": ks, "vs": vs}
    pos = jnp.array([3, 7])  # first causal row per batch entry
    got = Int8KV().attend_rows_causal(q, cache, pos)

    deq_k = kq.astype(jnp.float32) * ks[..., None]
    deq_v = vq.astype(jnp.float32) * vs[..., None]
    want = FloatKV().attend_rows_causal(q, {"k": deq_k, "v": deq_v}, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_int8_prefill_logits_close():
    _, prepared = _prepared()
    ids = jax.random.randint(jax.random.PRNGKey(4), (2, 16), 0, CFG.vocab_size)
    lo_f32, _ = forward_with_cache(prepared, ids, init_cache(CFG, 2, 24), 0, cfg=CFG)
    lo_i8, _ = forward_with_cache(
        prepared, ids, init_cache(CFG, 2, 24, "int8"), 0, cfg=CFG)
    assert _cos(lo_i8, lo_f32) > 0.999


def test_int8_incremental_decode_logits_track_f32():
    """Step-by-step decode with the int8 cache must track the f32-cache
    logits (cosine per step), feeding the F32 PATH'S tokens to both so
    errors cannot compound through token divergence."""
    _, prepared = _prepared(seed=1)
    ids = jax.random.randint(jax.random.PRNGKey(5), (1, 8), 0, CFG.vocab_size)
    n_new = 8
    c32 = init_cache(CFG, 1, 8 + n_new)
    ci8 = init_cache(CFG, 1, 8 + n_new, "int8")
    lo32, c32 = forward_with_cache(prepared, ids, c32, 0, cfg=CFG)
    loi8, ci8 = forward_with_cache(prepared, ids, ci8, 0, cfg=CFG)
    tok = jnp.argmax(lo32[:, -1], -1).astype(jnp.int32)
    for i in range(n_new):
        assert _cos(loi8[:, -1], lo32[:, -1]) > 0.999, f"step {i}"
        lo32, c32 = forward_with_cache(prepared, tok[:, None], c32, 8 + i, cfg=CFG)
        loi8, ci8 = forward_with_cache(prepared, tok[:, None], ci8, 8 + i, cfg=CFG)
        tok = jnp.argmax(lo32[:, -1], -1).astype(jnp.int32)


def test_make_generate_kv_dtypes_run_and_agree_mostly():
    """End-to-end greedy decode with f32 / bf16 / int8 caches: all run,
    and the quantized caches' token streams stay close to f32's (random
    tiny models have sub-0.1 top-1 margins, so a few flips are expected —
    wholesale divergence is not)."""
    _, prepared = _prepared(seed=2)
    ids = jax.random.randint(jax.random.PRNGKey(6), (2, 8), 0, CFG.vocab_size)
    n_new = 12
    outs = {}
    for name, kv in (("f32", None), ("bf16", jnp.bfloat16), ("int8", "int8")):
        gen = make_generate(CFG, max_new_tokens=n_new, kv_dtype=kv)
        outs[name] = np.asarray(gen(prepared, ids, jax.random.PRNGKey(0)))
    for name in ("bf16", "int8"):
        agree = (outs[name] == outs["f32"]).mean()
        assert agree >= 0.5, f"{name} cache diverged wholesale: {agree:.0%}"
