"""LoRA: low-rank adapters over the pure-pytree models.

Contracts under test:
  * identity at init (b = 0): merged model == base model, bit-for-bit;
  * training moves ONLY the adapter tree — the frozen base is untouched
    and the optimizer state is adapter-sized;
  * the same adapter recipe fits the per-layer AND the stacked layouts,
    and a stacked-layout merge serves through the standard decode path;
  * save/load round-trips the npz artifact exactly.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dnn_tpu import lora, train
from dnn_tpu.models import gpt, llama

CFG = gpt.GPTConfig(block_size=32, vocab_size=128, n_layer=2, n_head=4,
                    n_embd=32)


@pytest.fixture(scope="module")
def base():
    params = gpt.init(jax.random.PRNGKey(0), CFG)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 17), 0,
                                CFG.vocab_size, dtype=jnp.int32)
    return params, tokens


def test_identity_at_init(base):
    params, tokens = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    merged = lora.merge_lora(params, ad)
    want = gpt.make_apply(CFG)(params, tokens)
    got = gpt.make_apply(CFG)(merged, tokens)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_targets_cover_kernels_only(base):
    params, _ = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    # per block: attn qkv + attn proj + mlp fc + mlp proj = 4 kernels
    assert len(ad) == 4 * CFG.n_layer
    assert all(k.endswith("kernel") for k in ad)
    assert not any("wte" in k or "lm_head" in k or "ln" in k for k in ad)
    # exact size: r*(in+out) per adapted kernel (parameter efficiency is
    # a function of n_embd/rank; the toy model here is deliberately tiny)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    want = sum(4 * (leaf.shape[-2] + leaf.shape[-1])
               for path, leaf in flat
               if lora._path_str(path) in ad)
    n_adapter = sum(x.size for x in jax.tree.leaves(ad))
    assert n_adapter == want


def test_training_moves_only_adapters(base):
    params, tokens = base
    apply_fn = gpt.make_apply(CFG)
    loss_fn = lora.make_lora_loss(
        lambda p, b: train.next_token_loss(apply_fn, p, b), params)
    opt = optax.adam(1e-2)
    step = train.make_train_step(loss_fn, opt)
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    state = opt.init(ad)
    loss0 = float(loss_fn(ad, tokens))
    losses = []
    for _ in range(10):
        ad, state, loss = step(ad, state, tokens)
        losses.append(float(loss))
    assert losses[-1] < loss0 - 0.05, (loss0, losses)
    # optimizer state is adapter-sized, not model-sized
    n_state = sum(x.size for x in jax.tree.leaves(state)
                  if hasattr(x, "size"))
    n_adapter = sum(x.size for x in jax.tree.leaves(ad))
    assert n_state <= 2 * n_adapter + 16
    # merged-with-trained-adapters beats base on the fit batch
    merged = lora.merge_lora(params, ad)
    base_loss = float(train.next_token_loss(apply_fn, params, tokens))
    tuned_loss = float(train.next_token_loss(apply_fn, merged, tokens))
    assert tuned_loss < base_loss - 0.05


def test_stacked_layout_adapts_and_serves(base):
    """init_lora over the stacked layout: adapter leaves carry the (L,)
    stack axis, the merge batches over it, and the merged tree drives the
    standard decode path (zero inference-time overhead deployment)."""
    params, tokens = base
    prepared = gpt.prepare_stacked(params, CFG)
    ad = lora.init_lora(jax.random.PRNGKey(3), prepared, rank=4)
    assert len(ad) == 4  # one stacked entry per kernel site
    assert all(v["a"].shape[0] == CFG.n_layer for v in ad.values())
    # perturb b so the merge is non-trivial, then check the stacked merge
    # equals the per-layer merge composed through prepare_stacked
    ad = jax.tree.map(
        lambda x: x + 0.01 * jnp.arange(x.size, dtype=x.dtype
                                        ).reshape(x.shape), ad)
    merged_stacked = lora.merge_lora(prepared, ad)

    # mirror the stacked adapters back onto per-layer params
    per_layer_ad = {}
    for k in ad:
        site = k.replace("blocks/", "")
        for i in range(CFG.n_layer):
            per_layer_ad[f"h_{i}/{site}"] = {
                "a": ad[k]["a"][i], "b": ad[k]["b"][i]}
    merged_per_layer = lora.merge_lora(params, per_layer_ad)
    want = gpt.prepare_stacked(merged_per_layer, CFG)
    for a, b in zip(jax.tree.leaves(want), jax.tree.leaves(merged_stacked)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-6, atol=1e-6)

    from dnn_tpu.runtime.generate import make_generate

    toks = make_generate(CFG, max_new_tokens=4)(
        merged_stacked, tokens[:2, :5], jax.random.PRNGKey(4))
    assert np.asarray(toks).shape == (2, 4)


def test_llama_family_targets():
    cfg = llama.LlamaConfig(block_size=32, vocab_size=128, n_layer=2,
                            n_head=4, n_kv_head=2, n_embd=32, d_ff=64)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    ad = lora.init_lora(jax.random.PRNGKey(1), params, rank=2)
    # q,k,v,o + gate,up,down = 7 kernels per block
    assert len(ad) == 7 * cfg.n_layer
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 9), 0,
                             cfg.vocab_size, dtype=jnp.int32)
    want = llama.make_apply(cfg)(params, ids)
    got = llama.make_apply(cfg)(lora.merge_lora(params, ad), ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_save_load_roundtrip(tmp_path, base):
    params, _ = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    ad = jax.tree.map(lambda x: x + 0.5, ad)  # non-trivial b
    path = os.path.join(tmp_path, "adapter.npz")
    lora.save_lora(path, ad)
    back, alpha = lora.load_lora(path)
    assert alpha is None  # default-alpha artifact carries no override
    assert set(back) == set(ad)
    for k in ad:
        np.testing.assert_array_equal(np.asarray(back[k]["a"]),
                                      np.asarray(ad[k]["a"]))
        np.testing.assert_array_equal(np.asarray(back[k]["b"]),
                                      np.asarray(ad[k]["b"]))


def test_alpha_survives_roundtrip(tmp_path, base):
    """An adapter trained at non-default alpha must merge at the SAME
    strength after save/load — the scale is part of the artifact."""
    params, tokens = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    ad = jax.tree.map(lambda x: x + 0.1, ad)
    path = os.path.join(tmp_path, "adapter.npz")
    lora.save_lora(path, ad, alpha=16)
    back, alpha = lora.load_lora(path)
    assert alpha == 16.0
    want = gpt.make_apply(CFG)(lora.merge_lora(params, ad, alpha=16), tokens)
    got = gpt.make_apply(CFG)(lora.merge_lora(params, back, alpha=alpha),
                              tokens)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_explicit_embedding_target(base):
    """Explicitly targeting 'wte' adapts the embedding table (default
    targets exclude it)."""
    params, _ = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4,
                        targets=("wte", "qkv"))
    assert "wte/embedding" in ad
    assert sum(1 for k in ad if "qkv" in k) == CFG.n_layer


def test_layout_mismatch_raises(base):
    """Per-layer adapters onto stacked params must raise, not silently
    serve the un-tuned base model."""
    params, _ = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    prepared = gpt.prepare_stacked(params, CFG)
    with pytest.raises(ValueError, match="matched no param leaf"):
        lora.merge_lora(prepared, ad)


def test_empty_adapters_raise(base):
    params, _ = base
    with pytest.raises(ValueError, match="empty adapter"):
        lora.merge_lora(params, {})


def test_alpha_scales_delta(base):
    params, tokens = base
    ad = lora.init_lora(jax.random.PRNGKey(2), params, rank=4)
    ad = jax.tree.map(lambda x: x + 0.1, ad)
    m1 = lora.merge_lora(params, ad, alpha=4)    # scale 1.0
    m2 = lora.merge_lora(params, ad, alpha=8)    # scale 2.0
    d1 = m1["h_0"]["attn"]["qkv"]["kernel"] - params["h_0"]["attn"]["qkv"]["kernel"]
    d2 = m2["h_0"]["attn"]["qkv"]["kernel"] - params["h_0"]["attn"]["qkv"]["kernel"]
    np.testing.assert_allclose(np.asarray(d2), 2 * np.asarray(d1),
                               rtol=1e-5, atol=1e-6)
