"""KV-tier tests (ISSUE 15, dnn_tpu/kvtier): the radix prefix store,
block migration with the lease machine, and the serving integration.

Four families:
  * radix unit suite — insert/lookup/COW goldens against a FAKE
    allocator (no jax), refcount protection under eviction, leaf-LRU
    order, block-aligned vs ragged edges, concurrent admit/evict under
    the single-producer contract;
  * wire + lease — pack/unpack roundtrips (f32 / int8 / int4 nibble /
    bf16), corruption rejection, lease lifecycle incl. TTL expiry and
    the shm nonce proof; the KVLEASE protocol table both directions
    (the deleted-reclaim edge reproduces "blocks leak forever" as
    PRO002);
  * serving integration — radix admission parity with the uncached
    oracle (greedy AND seeded-sampled) through COW / full-hit /
    retire-time insertion, the row-capacity backoff golden, and
    export/adopt/stage cross-pool parity with zero leaked blocks;
  * donor death — a severed pull (chaos kv_migrate_fault / dead donor)
    must fall back loud (`kvtier_fallback`), re-prefill with ZERO
    token divergence, and leave the pool's block accounting at
    baseline.
"""

import threading

import numpy as np
import pytest

from dnn_tpu.kvtier.radix import RadixIndex
from dnn_tpu.kvtier.store import PrefixStore

BP = 4  # block_len for the pure-host suites


class FakeAllocator:
    """BlockAllocator-shaped double: refcount bookkeeping only."""

    def __init__(self):
        self.rc = {}

    def seed(self, blocks):
        for b in blocks:
            self.rc[b] = self.rc.get(b, 0) + 1

    def ref(self, blocks):
        for b in blocks:
            assert self.rc.get(b, 0) >= 1, f"ref on dead block {b}"
        for b in blocks:
            self.rc[b] += 1

    def free(self, blocks):
        for b in blocks:
            assert self.rc.get(b, 0) >= 1, f"free of dead block {b}"
        for b in blocks:
            self.rc[b] -= 1
            if self.rc[b] == 0:
                del self.rc[b]


def toks(*vals):
    return np.asarray(vals, np.int32)


def seq(n, start=1):
    return np.arange(start, start + n, dtype=np.int32)


# ----------------------------------------------------------------------
# radix unit suite
# ----------------------------------------------------------------------

def test_radix_insert_lookup_golden():
    ix = RadixIndex(BP, capacity=16)
    t = seq(12)  # 3 full chunks
    created, evicted = ix.insert(t, [10, 11, 12])
    assert [n.block for n in created] == [10, 11, 12] and not evicted
    # full path match
    m, cow_n, cow = ix.match(t)
    assert [n.block for n in m] == [10, 11, 12]
    assert cow_n == 0 and cow is None
    # shorter prompt: only covering chunks match
    m, cow_n, cow = ix.match(seq(8))
    assert [n.block for n in m] == [10, 11]
    # the 9..12 chunk of the full path agrees with a ragged tail
    m, cow_n, cow = ix.match(seq(10))
    assert [n.block for n in m] == [10, 11]
    assert cow is not None and cow.block == 12 and cow_n == 2
    # divergent tail: no boundary agreement
    m, cow_n, cow = ix.match(np.concatenate([seq(8), toks(99, 98)]))
    assert [n.block for n in m] == [10, 11] and cow_n == 0


def test_radix_cow_boundary_picks_longest_agreement():
    ix = RadixIndex(BP, capacity=16)
    base = seq(4)
    ix.insert(np.concatenate([base, toks(5, 6, 90, 91)]), [1, 2])
    ix.insert(np.concatenate([base, toks(5, 6, 7, 92)]), [1, 3])
    # query agrees with the second child on 3 tokens, first on 2
    m, cow_n, cow = ix.match(np.concatenate([base, toks(5, 6, 7, 8)]))
    assert [n.block for n in m] == [1]
    assert cow.block == 3 and cow_n == 3


def test_radix_insert_reuses_existing_nodes():
    ix = RadixIndex(BP, capacity=16)
    ix.insert(seq(8), [1, 2])
    created, _ = ix.insert(seq(12), [91, 92, 3])  # blocks 91/92 ignored
    assert [n.block for n in created] == [3]
    m, _n, _c = ix.match(seq(12))
    assert [n.block for n in m] == [1, 2, 3]


def test_radix_leaf_lru_eviction_order_scan_resistant():
    """Inserted nodes PARK at the LRU end (newest park evicts first —
    a novel-prompt scan cycles its own nodes through the eviction
    slot); only a MATCH promotes."""
    ix = RadixIndex(BP, capacity=16)
    ix.insert(seq(4, start=1), [1])
    ix.insert(seq(4, start=100), [2])
    ix.insert(seq(4, start=200), [3])
    ix.match(seq(4, start=1))    # touch 1 -> MRU
    v = ix.evict_lru_leaf()
    assert v.block == 3          # newest PARKED (never matched) first
    v = ix.evict_lru_leaf()
    assert v.block == 2
    v = ix.evict_lru_leaf()
    assert v.block == 1          # the matched node survives longest
    assert ix.evict_lru_leaf() is None


def test_radix_interior_nodes_not_evictable():
    ix = RadixIndex(BP, capacity=16)
    ix.insert(seq(12), [1, 2, 3])
    assert ix.evict_lru_leaf().block == 3   # deepest leaf first
    assert ix.evict_lru_leaf().block == 2
    assert ix.evict_lru_leaf().block == 1


def test_radix_capacity_evicts_on_insert():
    ix = RadixIndex(BP, capacity=2)
    ix.insert(seq(8), [1, 2])
    created, evicted = ix.insert(seq(8, start=100), [3, 4])
    # made room by evicting the old path's leaves; never over capacity
    assert ix.n_nodes <= 2
    assert {n.block for n in evicted} <= {1, 2}
    # the path being inserted is protected from its own eviction
    assert [n.block for n in created][:1] == [3]


def test_store_refcount_protects_shared_blocks():
    a = FakeAllocator()
    a.seed([7, 8])  # the "slot" holds one ref each
    st = PrefixStore(a, BP, capacity=8)
    st.insert(seq(8), [7, 8])
    assert a.rc == {7: 2, 8: 2}   # slot + store
    assert st.evict_one() and st.evict_one()
    assert a.rc == {7: 1, 8: 1}   # eviction dropped ONLY store refs
    assert not st.evict_one()


def test_store_block_hit_accounting_and_origin():
    a = FakeAllocator()
    a.seed([1, 2])
    st = PrefixStore(a, BP, capacity=8)
    st.insert(seq(8), [1, 2], origin="adopted")
    hit = st.lookup(seq(8))
    assert hit.shared == [1, 2] and hit.origins == ["adopted"] * 2
    assert hit.remote_used(2, False) == 2
    # lookup has NO counter side effects — admission reports what it
    # actually reused (a truncated or failed admission counts nothing)
    assert st.block_hits == 0
    st.note_reuse(2, hit.remote_used(2, False))
    assert st.block_hits == 2 and st.remote_block_hits == 2
    # truncation: only the first block got used
    assert hit.remote_used(1, False) == 1
    miss = st.lookup(seq(8, start=500))
    assert miss.shared == [] and st.block_hits == 2


def test_store_full_hit_needs_logit_row_and_alignment():
    a = FakeAllocator()
    a.seed([1, 2])
    st = PrefixStore(a, BP, capacity=8)
    lr = np.arange(5.0)
    st.insert(seq(8), [1, 2], logit_rows={1: lr})
    assert st.lookup(seq(8)).logit_row is lr          # aligned + row
    assert st.lookup(seq(7)).logit_row is None        # ragged
    a2 = FakeAllocator()
    a2.seed([3])
    st2 = PrefixStore(a2, BP, capacity=8)
    st2.insert(seq(4), [3])                           # no logit row
    assert st2.lookup(seq(4)).logit_row is None


def test_store_concurrent_scrape_during_admit_evict():
    """The single-producer contract: one thread mutates (insert/evict)
    while scrape-side readers hammer the counters — no exceptions, no
    negative reads (the gauges are GIL-atomic int loads)."""
    a = FakeAllocator()
    st = PrefixStore(a, BP, capacity=32)
    stop = threading.Event()
    errs = []

    def scraper():
        while not stop.is_set():
            try:
                assert st.n_blocks >= 0
                assert st.block_hits >= 0
            except Exception as e:  # noqa: BLE001
                errs.append(e)
                return

    th = threading.Thread(target=scraper, daemon=True)
    th.start()
    for i in range(300):
        blocks = [1000 + i * 2, 1001 + i * 2]
        a.seed(blocks)
        st.insert(seq(8, start=i * 10 + 1), blocks)
        st.lookup(seq(8, start=i * 10 + 1))
        if i % 3 == 0:
            st.evict_one()
        a.free(blocks)  # the "slot" retires
    stop.set()
    th.join(timeout=5)
    assert not errs


# ----------------------------------------------------------------------
# wire codec + lease machine
# ----------------------------------------------------------------------

def test_pack_unpack_roundtrip_f32_int8_int4_bf16():
    from dnn_tpu.kvtier import migrate as M

    rng = np.random.default_rng(0)
    cases = [
        ("float32", rng.standard_normal((2, 2, 3, BP, 5),
                                        ).astype(np.float32)),
        ("int8", rng.integers(-127, 128, (2, 2, 3, BP, 5),
                              ).astype(np.int8)),
        ("int4", rng.integers(-8, 8, (2, 2, 3, BP, 5),
                              ).astype(np.int8)),
    ]
    import ml_dtypes

    cases.append(("bfloat16", rng.standard_normal(
        (2, 2, 3, BP, 5)).astype(ml_dtypes.bfloat16)))
    for name, arr in cases:
        pl = {"tokens": seq(2 * BP), "block_len": BP,
              "leaves": {"k": arr},
              "logit_rows": {0: np.arange(7.0, dtype=np.float32)},
              "fingerprint": {"leaves": {
                  "k": [list(arr.shape), name]}}}
        back = M.unpack_blocks(M.pack_blocks(pl))
        np.testing.assert_array_equal(back["tokens"], pl["tokens"])
        if name == "bfloat16":
            np.testing.assert_array_equal(
                back["leaves"]["k"].view(np.uint16),
                arr.view(np.uint16))
        else:
            np.testing.assert_array_equal(back["leaves"]["k"], arr)
        np.testing.assert_array_equal(back["logit_rows"][0],
                                      pl["logit_rows"][0])
    # int4 ships nibble-packed: strictly under 1 byte/element on wire
    arr4 = cases[2][1]
    pl4 = {"tokens": seq(2 * BP), "block_len": BP,
           "leaves": {"k": arr4}, "logit_rows": {},
           "fingerprint": {"leaves": {"k": [list(arr4.shape),
                                            "int4"]}}}
    wire4 = M.pack_blocks(pl4)
    pl8 = dict(pl4, fingerprint={"leaves": {"k": [list(arr4.shape),
                                                  "int8"]}})
    wire8 = M.pack_blocks(pl8)
    assert wire4.size < wire8.size
    # saves half the leaf bytes, modulo a few header bytes ("nibble")
    assert wire8.size - wire4.size >= arr4.size // 2 - 16


def test_unpack_rejects_garbage_and_truncation():
    from dnn_tpu.kvtier import migrate as M

    with pytest.raises(ValueError, match="bad magic"):
        M.unpack_blocks(np.frombuffer(b"nonsense bytes!!", np.uint8))
    pl = {"tokens": seq(BP), "block_len": BP,
          "leaves": {"k": np.zeros((1, 1, 1, BP, 2), np.float32)},
          "logit_rows": {}, "fingerprint": {}}
    wire = M.pack_blocks(pl)
    with pytest.raises(ValueError, match="truncated"):
        M.unpack_blocks(wire[: wire.size - 8])


def test_lease_lifecycle_and_ttl_expiry():
    from dnn_tpu.kvtier import migrate as M

    lt = M.LeaseTable(ttl_s=30.0, use_shm=False)
    meta = lt.offer(b"payload-bytes")
    assert lt.fetch(meta["lease"]) == b"payload-bytes"
    assert lt.ack(meta["lease"]) and lt.n_leases == 0
    assert not lt.ack(meta["lease"])  # second ack: already gone
    # TTL expiry reclaims an abandoned offer (offered AND pulling)
    m2 = lt.offer(b"x" * 64)
    lt.fetch(m2["lease"])  # pulling
    assert lt.sweep(now=1e18) == 1
    with pytest.raises(KeyError):
        lt.fetch(m2["lease"])
    assert lt.n_leases == 0


def test_lease_shm_rung_nonce_proof():
    from dnn_tpu.kvtier import migrate as M

    pub = M.publish_shm(b"block-bytes")
    if pub is None:
        pytest.skip("no POSIX shm on this platform")
    name, nonce, seg = pub
    try:
        assert M.attach_shm(name, nonce, 11) == b"block-bytes"
        with pytest.raises(ValueError, match="nonce"):
            M.attach_shm(name, "00" * 16, 11)
    finally:
        seg.close()
        seg.unlink()


def test_kvlease_machine_clean_and_both_directions():
    """The declared table is sound, and deleting the expired state's
    reclaim edge reproduces 'staged blocks leak forever' as a PRO002
    model failure (the issue's required direction); deleting the
    expire edges strands `expired` as unreachable (PRO001)."""
    import dataclasses

    from dnn_tpu.analysis.protocol import KVLEASE, check_machine

    assert check_machine(KVLEASE) == []
    no_reclaim = dataclasses.replace(
        KVLEASE, edges=tuple(e for e in KVLEASE.edges
                             if e.event != "lease_reclaim"))
    rules = {f.rule for f in check_machine(no_reclaim)}
    assert "PRO002" in rules
    no_expire = dataclasses.replace(
        KVLEASE, edges=tuple(e for e in KVLEASE.edges
                             if e.event not in ("lease_expire",
                                                "lease_reclaim")))
    rules = {f.rule for f in check_machine(no_expire)}
    assert "PRO001" in rules


def test_chaos_plan_gains_donor_kill_fault():
    from dnn_tpu.chaos.plan import FaultPlan, standard_plan

    plan = standard_plan(donor_kill_at_s=12.0, donor_target="r0")
    kinds = [f.kind for f in plan.process_faults()]
    assert "kill_donor" in kinds
    # schema roundtrip (the probe ships plans as JSON)
    back = FaultPlan.from_dict(plan.to_dict())
    assert back == plan
    # the in-process migration fault parses too
    p2 = FaultPlan.from_dict({"faults": [
        {"kind": "kv_migrate_fault", "at_n": 0}]})
    assert p2.inprocess_faults()[0].kind == "kv_migrate_fault"


def test_directory_observe_locate_forget():
    from dnn_tpu.kvtier.directory import PrefixDirectory

    d = PrefixDirectory(BP, cap=64)
    t = seq(3 * BP)
    d.observe(t, "r0")
    loc = d.locate(t)
    assert loc.replica == "r0" and loc.n_blocks == 3
    # deeper knowledge wins; ragged tails fall back to full blocks
    assert d.locate(np.concatenate([t, toks(99)])).n_blocks == 3
    assert d.locate(t[: 2 * BP]).n_blocks == 2
    assert d.locate(seq(BP, start=900)) is None
    # latest claim wins
    d.observe(t, "r1")
    assert d.locate(t).replica == "r1"
    assert d.forget("r1") == 3
    assert d.locate(t) is None


# ----------------------------------------------------------------------
# serving integration (jax from here down)
# ----------------------------------------------------------------------

SBP = 8  # serving block_len


@pytest.fixture(scope="module")
def served():
    import jax

    from dnn_tpu.models import gpt

    cfg = gpt.GPTConfig(block_size=64, vocab_size=256, n_layer=2,
                        n_head=4, n_embd=64)
    prepared = gpt.prepare_stacked(gpt.init(jax.random.PRNGKey(0), cfg),
                                   cfg)
    return cfg, prepared


def _radix_pool(served, **kw):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg, prepared = served
    args = dict(slots=2, max_len=64, prompt_pad=16, kv="paged",
                paged_blocks=24, block_len=SBP, prefix_cache=16)
    args.update(kw)
    return ContinuousBatcher(cfg, prepared, **args)


def _oracle(served, prompt, max_new, **sub):
    from dnn_tpu.runtime.serving import ContinuousBatcher

    cfg, prepared = served
    ref = ContinuousBatcher(cfg, prepared, slots=1, max_len=64,
                            prompt_pad=16)
    r = ref.submit(prompt, max_new, **sub)
    return ref.drain()[r]


def test_cow_boundary_saves_chunks_with_exact_parity(served):
    srv = _radix_pool(served)
    sys_p = seq(21)  # 2 full blocks + 5 ragged (bp=8)
    a = np.concatenate([sys_p, toks(30, 31, 32)])
    b = np.concatenate([sys_p, toks(40, 41, 42, 43)])
    ra = srv.submit(a, max_new_tokens=5)
    srv.drain()
    c0 = srv.prefill_chunks_run
    rb = srv.submit(b, max_new_tokens=5, seed=3, temperature=0.8)
    out = srv.drain()
    # cold = 2 chunks (25 tokens / pad 16); the COW boundary resumes
    # mid-block at the divergence -> ONE chunk
    assert srv.prefill_chunks_run - c0 == 1
    assert srv.prefix_hits == 1
    np.testing.assert_array_equal(out[ra], _oracle(served, a, 5))
    np.testing.assert_array_equal(
        out[rb], _oracle(served, b, 5, seed=3, temperature=0.8))


def test_block_aligned_full_hit_zero_chunks(served):
    srv = _radix_pool(served)
    p = seq(16)  # exactly 2 blocks, NOT chunk-count aligned cases too
    r1 = srv.submit(p, max_new_tokens=4)
    srv.drain()
    c0 = srv.prefill_chunks_run
    r2 = srv.submit(p, max_new_tokens=4)
    out = srv.drain()
    assert srv.prefill_chunks_run == c0  # zero chunks: stored logit row
    np.testing.assert_array_equal(out[r1], out[r2])
    np.testing.assert_array_equal(out[r2], _oracle(served, p, 4))


def test_ragged_same_prompt_recomputes_only_tail(served):
    srv = _radix_pool(served)
    p = seq(19)  # 2 blocks + 3 ragged
    srv.submit(p, max_new_tokens=4)
    srv.drain()
    c0 = srv.prefill_chunks_run
    r2 = srv.submit(p, max_new_tokens=4, seed=9, temperature=1.0)
    out = srv.drain()
    assert srv.prefill_chunks_run - c0 == 1  # the ragged tail chunk
    np.testing.assert_array_equal(
        out[r2], _oracle(served, p, 4, seed=9, temperature=1.0))


def test_retire_time_insertion_serves_chat_followup(served):
    srv = _radix_pool(served)
    t1 = seq(16)
    rt = srv.submit(t1, max_new_tokens=8)
    o = srv.drain()
    follow = np.concatenate([t1, o[rt].astype(np.int32), toks(5, 6, 7)])
    c0 = srv.prefill_chunks_run
    rf = srv.submit(follow, max_new_tokens=4)
    out = srv.drain()
    cold_chunks = -(-len(follow) // 16)
    assert srv.prefill_chunks_run - c0 < cold_chunks
    np.testing.assert_array_equal(out[rf], _oracle(served, follow, 4))


def test_row_capacity_backoff_near_full_row(served):
    """A prompt near max_len whose resume point is unaligned: the
    chunk loop must round the resume down (never overhang the
    transient row — a clamped dynamic update corrupts silently), with
    parity intact."""
    srv = _radix_pool(served, slots=1, paged_blocks=32)
    base = seq(21)  # ragged boundary -> mid-block resume candidates
    long_a = np.concatenate([base, seq(34, start=100)])  # 55 tokens
    long_b = np.concatenate([base, seq(37, start=200)])  # 58 tokens
    ra = srv.submit(long_a, max_new_tokens=3)
    srv.drain()
    rb = srv.submit(long_b, max_new_tokens=3)
    out = srv.drain()
    np.testing.assert_array_equal(out[ra],
                                  _oracle(served, long_a, 3))
    np.testing.assert_array_equal(out[rb],
                                  _oracle(served, long_b, 3))


def test_export_adopt_parity_and_block_accounting(served):
    srv = _radix_pool(served)
    ado = _radix_pool(served)
    p = seq(16)
    r = srv.submit(p, max_new_tokens=6, seed=7, temperature=0.9)
    want = srv.drain()[r]
    payload = srv.kvtier_export(p)
    assert payload["leaves"]["k"].shape[1] == 2  # 2 blocks
    used_before = ado._allocator.n_used
    assert ado.kvtier_adopt(payload) == 2
    assert ado._allocator.n_used == used_before + 2  # store-held only
    c0 = ado.prefill_chunks_run
    g = ado.submit(p, max_new_tokens=6, seed=7, temperature=0.9)
    got = ado.drain()[g]
    assert ado.prefill_chunks_run == c0  # full hit off adopted blocks
    np.testing.assert_array_equal(got, want)
    # cross-replica accounting: both hits were adopted-origin
    assert ado._prefix_store.remote_block_hits == 2
    assert ado._kvtier_remote_ratio_read() == 1.0
    # re-adopting the same payload is a dedup no-op
    assert ado.kvtier_adopt(payload) == 0


def test_adopt_rejects_geometry_mismatch(served):
    srv = _radix_pool(served)
    other = _radix_pool(served, kv_dtype="int8")
    p = seq(16)
    srv.submit(p, max_new_tokens=2)
    srv.drain()
    payload = srv.kvtier_export(p)
    with pytest.raises(ValueError, match="geometry mismatch"):
        other.kvtier_adopt(payload)


def test_int8_blocks_migrate_as_is(served):
    d8 = _radix_pool(served, kv_dtype="int8")
    a8 = _radix_pool(served, kv_dtype="int8")
    p = seq(16)
    r = d8.submit(p, max_new_tokens=5)
    want = d8.drain()[r]
    payload = d8.kvtier_export(p)
    assert set(payload["leaves"]) == {"k", "v", "ks", "vs"}
    from dnn_tpu.kvtier import migrate as M

    wire = M.pack_blocks(payload)
    a8.kvtier_adopt(M.unpack_blocks(wire))
    g = a8.submit(p, max_new_tokens=5)
    np.testing.assert_array_equal(a8.drain()[g], want)


def test_stage_prefix_then_admission_hits(served):
    srv = _radix_pool(served)
    p = seq(24)
    stats = srv.stage_prefix(p)
    assert stats["staged_blocks"] == 3
    # idempotent: a second stage computes nothing
    assert srv.stage_prefix(p)["staged_blocks"] == 0
    c0 = srv.prefill_chunks_run
    r = srv.submit(p, max_new_tokens=4)
    out = srv.drain()
    assert srv.prefill_chunks_run == c0  # block-aligned full hit
    np.testing.assert_array_equal(out[r], _oracle(served, p, 4))


def test_donor_death_mid_migration_zero_divergence_zero_leaks(served):
    """The chaos leg, in-process: the donor dies between lease and
    fetch (expired lease), the adopter's pull fails, and the follow-up
    admission re-prefills with identical tokens and baseline block
    accounting — nothing adopted, nothing leaked."""
    from dnn_tpu.kvtier import migrate as M

    donor = _radix_pool(served)
    ado = _radix_pool(served)
    p = seq(16)
    r = donor.submit(p, max_new_tokens=5)
    want = donor.drain()[r]
    payload = donor.kvtier_export(p)
    lt = M.LeaseTable(ttl_s=30.0, use_shm=False)
    meta = lt.offer(M.pack_blocks(payload).tobytes())
    lt.sweep(now=1e18)  # the donor's TTL fires: lease expired

    class DeadDonorClient:
        def kv_lease(self, tokens, timeout=None):
            return dict(meta)  # the offer raced the death

        def kv_fetch(self, lease_id, timeout=None):
            raise KeyError(lease_id)  # donor gone / lease reclaimed

        def kv_ack(self, lease_id, timeout=None):
            raise ConnectionError("donor dead")

    used0 = ado._allocator.n_used
    hw0 = ado._allocator.high_water
    with pytest.raises(Exception):
        M.pull_blocks(DeadDonorClient(), p)
    # nothing adopted, nothing leaked: accounting untouched
    assert ado._allocator.n_used == used0
    assert ado._allocator.high_water == hw0
    assert ado._prefix_store.n_blocks == 0
    # the re-prefill produces the identical stream
    g = ado.submit(p, max_new_tokens=5)
    np.testing.assert_array_equal(ado.drain()[g], want)


def test_chaos_kv_migrate_fault_severs_pull_deterministically():
    from dnn_tpu.chaos.inject import Injector
    from dnn_tpu.chaos.plan import FaultPlan

    inj = Injector(FaultPlan.from_dict(
        {"faults": [{"kind": "kv_migrate_fault", "at_n": 1,
                     "count": 1}]}))
    inj.kv_migrate()  # n=0: clean
    with pytest.raises(ConnectionError, match="donor death"):
        inj.kv_migrate()  # n=1: severed
    inj.kv_migrate()  # n=2: clean again — exactly one firing


def test_kvput_inbox_ttl_sweep(served):
    """Satellite: staged kvput handoffs expire — an abandoned prefill
    cannot pin its payload forever (kvput_expired flight event)."""
    import time as _time

    from dnn_tpu import obs
    from dnn_tpu.runtime.lm_server import LMServer

    cfg, prepared = served
    srv = LMServer(cfg, prepared, slots=2, max_len=64, prompt_pad=16,
                   kv_handoff_ttl_s=5.0)
    try:
        rec = obs.flight.recorder()
        srv._kv_handoff["fresh"] = ({"prompt_len": 4},
                                    _time.monotonic())
        srv._kv_handoff["stale"] = ({"prompt_len": 9},
                                    _time.monotonic() - 99.0)
        srv._sweep_kv_handoffs()
        assert "fresh" in srv._kv_handoff
        assert "stale" not in srv._kv_handoff
        evs = [e for e in rec.events(kind="kvput_expired")
               if e.get("key") == "stale"]
        assert evs and evs[-1]["prompt_len"] == 9
    finally:
        srv.close()


def test_worker_control_op_runs_on_busy_pool(served):
    """Control ops (the kvtier seam) apply between steps even while
    slots decode — and fail fast once the worker is dead."""
    from dnn_tpu.runtime.lm_server import LMServer

    cfg, prepared = served
    srv = LMServer(cfg, prepared, slots=2, max_len=64, prompt_pad=16,
                   kv="paged", paged_blocks=24, block_len=SBP,
                   prefix_cache=16)
    try:
        fut = srv.worker.submit(seq(8), 16, None)
        cfut = srv.worker.submit_control(
            lambda: srv.batcher.stage_prefix(seq(16, start=100)))
        stats = cfut.result(timeout=30)
        assert stats["staged_blocks"] == 2
        assert fut.result(timeout=30) is not None
    finally:
        srv.close()
    dead = srv.worker.submit_control(lambda: 1)
    with pytest.raises(Exception):
        dead.result(timeout=5)
