"""Multi-host (jax.distributed / DCN) support.

Unit tests cover config parsing and process-id resolution; the integration
test launches TWO real OS processes that join one jax.distributed job over
localhost (the DCN story on one machine — the TPU-native analog of the
reference's 'N localhost processes' deployment, readme.md:87) and run a
global-mesh psum spanning both processes.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from dnn_tpu.config import TopologyConfig
from dnn_tpu.parallel.multihost import (
    DistributedConfig,
    initialize_from_config,
    resolve_process_id,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_config_parses_distributed_block():
    cfg = TopologyConfig.from_dict({
        "nodes": [{"id": "a", "part_index": 0, "address": "h1:9000"},
                  {"id": "b", "part_index": 1, "address": "h2:9000"}],
        "num_parts": 2,
        "distributed": {"coordinator_address": "h1:9255", "num_processes": 2},
    })
    assert cfg.distributed.coordinator_address == "h1:9255"
    assert cfg.distributed.num_processes == 2
    assert cfg.distributed.process_id is None


def test_config_without_distributed_is_none():
    cfg = TopologyConfig.from_dict({"nodes": [], "num_parts": 1})
    assert cfg.distributed is None


def test_resolve_process_id_precedence(monkeypatch):
    dist = DistributedConfig("h:1", 2, process_id=1)
    assert resolve_process_id(dist, override=0) == 0  # CLI wins
    assert resolve_process_id(dist) == 1              # then config
    dist2 = DistributedConfig("h:1", 2)
    monkeypatch.setenv("DNN_TPU_PROCESS_ID", "7")
    assert resolve_process_id(dist2) == 7             # then env
    monkeypatch.delenv("DNN_TPU_PROCESS_ID")
    with pytest.raises(ValueError, match="process_id not set"):
        resolve_process_id(dist2)


def test_single_process_is_noop():
    assert initialize_from_config(None) is False
    assert initialize_from_config(DistributedConfig("h:1", 1)) is False


# ----------------------------------------------------------------------
# capability probe: does THIS jaxlib's CPU client have a cross-process
# collective transport (gloo)? Answered structurally, not by matching
# error prose: a pure-jax 2-process job inits jax.distributed, then runs
# exactly one boundary-crossing collective inside try/except and exits
# with a SENTINEL code when only the collective raises. The verdict is
# cached module-wide — one probe pair per session, however many tests
# come to depend on it.
# ----------------------------------------------------------------------

_PROBE = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(coordinator_address=sys.argv[2],
                               num_processes=2,
                               process_id=int(sys.argv[1]))
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("d",))
    local = [jax.device_put(jnp.ones((1,)), d)
             for d in jax.local_devices()]
    garr = jax.make_array_from_single_device_arrays(
        (4,), NamedSharding(mesh, P("d")), local)
    try:
        float(jax.jit(lambda x: jnp.sum(x),
                      out_shardings=NamedSharding(mesh, P()))(garr))
    except Exception:
        sys.exit(42)  # init succeeded; the COLLECTIVE is what's missing
""")

_PROBE_SENTINEL = 42
_probe_verdict = {}


def _cpu_multiprocess_collectives_supported() -> bool:
    """True unless the probe pair structurally reports the sentinel
    (distributed init worked, the cross-process collective raised). Any
    OTHER probe failure — init timeout, crash — deliberately reads as
    'supported' so the real test runs and surfaces full diagnostics
    instead of a silent skip."""
    if "ok" in _probe_verdict:
        return _probe_verdict["ok"]
    import socket
    import tempfile

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "probe.py")
        with open(script, "w") as f:
            f.write(_PROBE)
        procs = [
            subprocess.Popen(
                [sys.executable, script, str(pid), f"127.0.0.1:{port}"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env)
            for pid in (0, 1)
        ]
        try:
            for p in procs:
                p.wait(timeout=120)
        except subprocess.TimeoutExpired:
            pass
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
    _probe_verdict["ok"] = not any(
        p.returncode == _PROBE_SENTINEL for p in procs)
    return _probe_verdict["ok"]


_WORKER = textwrap.dedent("""
    import json, os, sys
    sys.path.insert(0, {repo!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

    import jax
    jax.config.update("jax_platforms", "cpu")
    from dnn_tpu.parallel.multihost import (
        DistributedConfig, initialize_from_config, is_multihost, process_info,
    )

    pid = int(sys.argv[1])
    dist = DistributedConfig({coord!r}, 2)
    assert initialize_from_config(dist, process_id=pid)
    assert is_multihost()
    info = process_info()
    assert info["process_count"] == 2
    assert info["global_devices"] == 4  # 2 hosts x 2 local devices

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from dnn_tpu.parallel.mesh import DATA_AXIS, make_mesh

    # global mesh over BOTH processes' devices; each host feeds its local
    # shard, the psum crosses the process boundary
    mesh = make_mesh({{DATA_AXIS: 4}}, jax.devices())
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    local = [
        jax.device_put(
            jnp.full((1,), float(jax.process_index() * 2 + i + 1)), d
        )
        for i, d in enumerate(jax.local_devices())
    ]
    garr = jax.make_array_from_single_device_arrays((4,), sharding, local)
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(garr)
    # values are 1,2 on process 0 and 3,4 on process 1 -> 10
    assert float(total) == 10.0, float(total)
    print(json.dumps({{"pid": pid, "total": float(total)}}))
""")


def test_two_process_distributed_psum(tmp_path):
    """Two real processes, one jax.distributed job, one global mesh, a sum
    crossing the process boundary."""
    import socket

    if not _cpu_multiprocess_collectives_supported():
        # a toolchain limit (no gloo in this jaxlib's CPU client), not a
        # framework bug; the same job spec runs on TPU pods and
        # gloo-enabled builds — verdict from the structural probe above
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(_WORKER.format(repo=REPO, coord=coord))

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=180))
    finally:
        # a hung/crashed worker must not leak, and BOTH workers' stderr must
        # surface (the failing one holds the root cause)
        for p in procs:
            if p.poll() is None:
                p.kill()
                outs.append(p.communicate())
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, "worker failed:\n" + "\n---\n".join(
            f"rc={q.returncode}\n{o}\n{e}" for q, (o, e) in zip(procs, outs)
        )
    results = [json.loads(out.strip().splitlines()[-1]) for out, _ in outs]
    assert all(r["total"] == 10.0 for r in results)
