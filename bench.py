"""Benchmark harness — prints ONE JSON line.

Headline metric: GPT-2 small forward throughput (tokens/sec) on one chip,
bf16 compute, stacked-block layout (the same compiled program the pipeline
runtime shards across chips).

Baseline: the reference runs its models as torch nn.Modules on
cuda-if-available-else-cpu (/root/reference/node.py:25); on this machine
that means torch CPU. We time the same GPT-2 architecture as a torch CPU
forward (HF GPT2LMHeadModel instantiated from config — no download) and
report vs_baseline = ours / torch_cpu. If torch is unavailable, the
baseline falls back to this framework's own forward pinned to the host CPU
backend (noted in the metric name).
"""

import json
import time

import jax
import jax.numpy as jnp

BATCH, SEQ = 8, 512


def _time_fn(fn, *args, n1=4, n2=12, trials=3):
    """Per-call wall time via the two-point slope method.

    On this machine the TPU sits behind a tunnel where
    `jax.block_until_ready` returns before device execution finishes, so
    naive timing measures dispatch only. Instead: queue N calls, force the
    dependency chain with a 1-element host read of the last output (device
    execution is in-order, so that read completes only after all N), and
    take (t(n2) - t(n1)) / (n2 - n1) so the constant tunnel RTT and
    transfer cost cancel.

    Validity guards (first-measurement effects were observed to skew a
    single slope by up to 2x in either direction): warm up past compile
    AND past the first few post-compile dispatches, evaluate t(n1) before
    t(n2) in a fixed order, and report the median slope of `trials`
    repeats.
    """
    import numpy as _np

    def run(n):
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        leaf = jax.tree.leaves(out)[0]
        _np.asarray(leaf.ravel()[0])  # scalar pull -> full sync
        return time.perf_counter() - t0

    run(2)  # compile
    run(n1)  # absorb post-compile first-dispatch overhead
    slopes = []
    for _ in range(trials):
        t1 = run(n1)
        t2 = run(n2)
        slopes.append((t2 - t1) / (n2 - n1))
    slopes.sort()
    return slopes[len(slopes) // 2]


def bench_ours():
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    fn = jax.jit(gpt.make_apply_stacked(cfg, compute_dtype=jnp.bfloat16))
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, SEQ), 0, cfg.vocab_size, dtype=jnp.int32
    )
    dt = _time_fn(fn, prepared, ids)
    return BATCH * SEQ / dt


def bench_torch_cpu():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    model = GPT2LMHeadModel(GPT2Config())  # gpt2-small shape, random init
    model.eval()
    ids = torch.randint(0, 50257, (BATCH, SEQ))
    with torch.no_grad():
        model(ids)  # warmup
        t0 = time.perf_counter()
        for _ in range(2):
            model(ids)
        dt = (time.perf_counter() - t0) / 2
    return BATCH * SEQ / dt


def bench_jax_cpu():
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2"]
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        prepared = gpt.prepare_stacked(params, cfg)
        fn = jax.jit(gpt.make_apply_stacked(cfg))
        ids = jax.random.randint(
            jax.random.PRNGKey(1), (BATCH, SEQ), 0, cfg.vocab_size, dtype=jnp.int32
        )
        dt = _time_fn(fn, prepared, ids, n1=1, n2=3)
    return BATCH * SEQ / dt


def main():
    ours = bench_ours()
    try:
        baseline = bench_torch_cpu()
        metric = "gpt2_fwd_tokens_per_sec_per_chip_vs_torch_cpu"
    except Exception:
        baseline = bench_jax_cpu()
        metric = "gpt2_fwd_tokens_per_sec_per_chip_vs_jax_cpu"
    print(json.dumps({
        "metric": metric,
        "value": round(ours, 1),
        "unit": "tokens/s",
        "vs_baseline": round(ours / baseline, 2),
    }))


if __name__ == "__main__":
    main()
