"""Benchmark harness — prints ONE JSON line.

Headline metric: GPT-2 small forward throughput (tokens/sec) on one chip,
bf16 compute, stacked-block layout (the same compiled program the pipeline
runtime shards across chips).

Baseline: the reference runs its models as torch nn.Modules on
cuda-if-available-else-cpu (/root/reference/node.py:25); on this machine
that means torch CPU. We time the same GPT-2 architecture as a torch CPU
forward (HF GPT2LMHeadModel instantiated from config — no download) and
report vs_baseline = ours / torch_cpu. If torch is unavailable, the
baseline falls back to this framework's own forward pinned to the host CPU
backend (noted in the metric name).
"""

import json
import time

import jax
import jax.numpy as jnp

BATCH, SEQ = 8, 512


from dnn_tpu.utils.timing import device_time as _time_fn  # shared harness


def bench_ours(light: bool = False):
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2"]
    params = gpt.init(jax.random.PRNGKey(0), cfg)
    prepared = gpt.prepare_stacked(params, cfg)
    # serving configuration: bf16 operands AND bf16 logit store (f32
    # accumulation) — the f32 logit write is the forward's largest HBM
    # store; rounding it to bf16 measures +11% end-to-end (see gpt.head)
    fn = jax.jit(gpt.make_apply_stacked(
        cfg, compute_dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16
    ))
    ids = jax.random.randint(
        jax.random.PRNGKey(1), (BATCH, SEQ), 0, cfg.vocab_size, dtype=jnp.int32
    )
    # light: the CPU-fallback path (emulated bf16 is ~seconds per forward;
    # the slope method's usual rep counts would blow the bench budget)
    dt = _time_fn(fn, prepared, ids, n1=1, n2=2) if light \
        else _time_fn(fn, prepared, ids)
    return BATCH * SEQ / dt


def bench_torch_cpu():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    model = GPT2LMHeadModel(GPT2Config())  # gpt2-small shape, random init
    model.eval()
    ids = torch.randint(0, 50257, (BATCH, SEQ))
    with torch.no_grad():
        model(ids)  # warmup
        t0 = time.perf_counter()
        for _ in range(2):
            model(ids)
        dt = (time.perf_counter() - t0) / 2
    return BATCH * SEQ / dt


def bench_jax_cpu():
    from dnn_tpu.models import gpt

    cfg = gpt.PRESETS["gpt2"]
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        params = gpt.init(jax.random.PRNGKey(0), cfg)
        prepared = gpt.prepare_stacked(params, cfg)
        fn = jax.jit(gpt.make_apply_stacked(cfg))
        ids = jax.random.randint(
            jax.random.PRNGKey(1), (BATCH, SEQ), 0, cfg.vocab_size, dtype=jnp.int32
        )
        dt = _time_fn(fn, prepared, ids, n1=1, n2=3)
    return BATCH * SEQ / dt


def _backend_alive(deadlines_s=(90.0, 180.0, 300.0),
                   backoff_s: float = 30.0) -> bool:
    """Bounded retry-with-backoff around the subprocess device probe —
    the probe itself is the serving watchdog's
    (dnn_tpu/obs/watchdog.subprocess_device_probe): one definition of
    "the chip answered" shared by the bench and the LM daemon's
    /statusz. Round-2 lesson (BENCH_r02.json, rc=1): a wedged TPU plugin
    hangs at backend init inside the first device op — in-process there
    is nothing to catch; the subprocess turns "hangs forever" into a
    detectable timeout. Round-3 lesson (BENCH_r03.json): a single
    attempt means one TRANSIENT wedge (driver restart, tunnel blip)
    costs the round's TPU headline. Deadlines ESCALATE so a
    slow-but-healthy cold init (plugin bringup + first-op compile can
    take minutes) is never mistaken for a wedge: the last attempt allows
    300 s, beyond the longest healthy init observed, while a genuinely
    dead chip still falls back to the honest CPU row in ~11 min worst
    case. Every failed attempt lands in the flight ring and the
    bench.probe_failures_total counter (machine-readable outcomes, not
    free-text notes — the round driver reads them off the JSON row)."""
    import sys

    from dnn_tpu import obs
    from dnn_tpu.obs.watchdog import subprocess_device_probe

    n = len(deadlines_s)
    for i, deadline in enumerate(deadlines_s):
        ok, detail, timed_out = subprocess_device_probe(deadline)
        if ok:
            if i:  # recovered after failures: record the flap too
                obs.flight.record("probe_recovered", attempt=i + 1)
            return True
        m = obs.metrics()
        if m is not None:
            m.inc("bench.probe_failures_total")
        obs.flight.record("probe_fail", attempt=i + 1, attempts=n,
                          deadline_s=deadline, detail=detail,
                          timed_out=timed_out)
        print(f"[bench] backend probe attempt {i + 1}/{n} failed "
              f"({detail})", file=sys.stderr)
        if timed_out and i + 1 < n:
            # WEDGED (hung probe), not merely unhealthy: invoke the
            # supervisor's device-restart path — a fresh subprocess
            # re-initializing the plugin from nothing with the longest
            # healthy-cold-init deadline — and count its success as the
            # round's recovery instead of burning the remaining ladder
            # (the failure shape that cost BENCH_r03–r05 their on-chip
            # rows). recover_backend records supervisor_device_restart
            # flight events either way.
            from dnn_tpu.chaos.supervisor import recover_backend

            r_ok, r_detail = recover_backend(
                deadline_s=max(deadlines_s))
            if r_ok:
                obs.flight.record("probe_recovered", attempt=i + 1,
                                  via="supervisor_device_restart")
                print("[bench] backend recovered via supervisor "
                      "restart path", file=sys.stderr)
                return True
            print(f"[bench] supervisor restart path failed "
                  f"({r_detail})", file=sys.stderr)
        if i + 1 < n:
            time.sleep(backoff_s * (i + 1))
    obs.flight.record("probe_exhausted", attempts=n)
    return False


def _last_good_tpu_reference(path=None):
    """The most recent COMMITTED on-chip headline from benchmarks/
    RESULTS.md, or None. Round-4 lesson: the chip answered the builder's
    session and wedged before the driver's, so BENCH_r04.json carried
    only the CPU fallback even though an on-chip table existed from hours
    earlier. When the probe ladder exhausts, this echo rides along on the
    fallback row (labeled, provenance-stamped — never mixed into the
    fresh measurement) so a wedged-chip round still surfaces a
    TPU-credible number."""
    import os
    import re

    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "RESULTS.md")
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return None
    head = re.search(r"Generated at commit `([^`]+)` on ([^;]+); "
                     r"device-section platform: ([^.\n]+)", text)
    if not head or "tpu" not in head.group(3):
        return None  # no on-chip table to echo
    row = re.search(r"\| gpt2_fwd \| tokens_per_sec \| ([0-9.]+) \| "
                    r"([0-9.]+%|—) \| tpu \| ([^|\n]*)", text)
    if not row:
        return None
    # a CARRIED row (off-chip refresh cycles re-stamp the table header
    # with the refresh commit) names its own measurement vintage in a
    # provenance= detail — that, not the header, is when this number
    # was actually measured on chip
    commit, date = head.group(1), head.group(2).strip()
    carried = re.match(r"provenance=(\S+) ([^,]+)",
                       row.group(3).strip())
    if carried:
        commit, date = carried.group(1), carried.group(2).strip()
    ref = {
        "metric": "gpt2_fwd_tokens_per_sec_per_chip",
        "value": float(row.group(1)),
        "commit": commit,
        "date": date,
        "note": "last committed on-chip measurement (benchmarks/"
                "RESULTS.md), NOT measured this run",
    }
    if row.group(2) != "—":
        ref["mfu"] = round(float(row.group(2).rstrip("%")) / 100, 4)
    return ref


def _previous_round_ratio(repo_dir=None):
    """The latest committed round's vs_baseline (BENCH_r*.json), for
    drift detection: the r4->r5 ratio swing (0.97 -> 0.84) went two
    rounds uninterrogated because nothing echoed the history next to the
    fresh number. Returns {"round", "vs_baseline"} or None."""
    import os
    import re

    repo_dir = repo_dir or os.path.dirname(os.path.abspath(__file__))
    best = None
    for name in os.listdir(repo_dir):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if not m:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, name)
    if best is None:
        return None
    try:
        with open(os.path.join(repo_dir, best[1])) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(obj, dict):
        return None
    row = obj
    if "vs_baseline" not in row and isinstance(obj.get("tail"), str):
        # driver format: the bench's printed JSON line rides inside the
        # captured "tail" text — take the last parseable line
        row = {}
        for line in obj["tail"].splitlines():
            if line.startswith("{"):
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    pass
    ratio = row.get("vs_baseline")
    if ratio is None:
        return None
    return {"round": best[0], "vs_baseline": ratio,
            "metric": row.get("metric")}


def _refresh_results_table():
    """On a HEALTHY TPU probe, auto-invoke the full suite with resume
    semantics and regenerate RESULTS.md + the README table — the first
    healthy-chip session refreshes the canonical artifact with zero
    human judgment (VERDICT r5 next-round #1). Runs AFTER the headline
    JSON line is printed, so a wedge mid-suite can never cost the round
    its number; all child output goes to stderr. Disable with
    DNN_BENCH_AUTORUN=0."""
    import os
    import subprocess
    import sys

    if os.environ.get("DNN_BENCH_AUTORUN", "1") == "0":
        return
    run_all = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "benchmarks", "run_all.py")
    timeout = int(os.environ.get("DNN_BENCH_AUTORUN_TIMEOUT", "14400"))
    print("[bench] healthy backend: refreshing benchmarks/RESULTS.md via "
          "run_all.py --resume", file=sys.stderr)
    try:
        rc = subprocess.call([sys.executable, run_all, "--resume"],
                             stdout=sys.stderr, stderr=sys.stderr,
                             timeout=timeout)
        print(f"[bench] run_all --resume exited rc={rc}", file=sys.stderr)
    except subprocess.TimeoutExpired:
        print(f"[bench] run_all --resume exceeded {timeout}s; partial "
              "rows persist in benchmarks/.bench_rows.jsonl for the next "
              "--resume", file=sys.stderr)


def main(argv=None):
    import sys

    args = list(sys.argv[1:] if argv is None else argv)
    require = None
    if "--require-substrate" in args:
        # contract flag (ROADMAP item 5a prep): the round driver states
        # the substrate this round's trajectory needs; a probe fallback
        # then marks the row ok=false and exits nonzero instead of
        # silently polluting the TPU trend with a CPU number
        idx = args.index("--require-substrate")
        try:
            require = args[idx + 1]
        except IndexError:
            print("--require-substrate needs a value (tpu|cpu)",
                  file=sys.stderr)
            return 2
        if require not in ("tpu", "cpu"):
            print(f"--require-substrate must be tpu|cpu, got "
                  f"{require!r}", file=sys.stderr)
            return 2
    fell_back = not _backend_alive()
    if fell_back:
        # default (TPU) backend is wedged: force CPU before first use so
        # this process can still measure and report (one JSON line either
        # way; the row carries platform + a note)
        jax.config.update("jax_platforms", "cpu")
    # substrate, not history: a TPU-less host passes the probe on a
    # healthy CPU backend yet must still take the light timing path AND
    # the cpu-marked metric key below
    on_cpu = jax.default_backend() == "cpu"
    baseline_fn, metric = None, None
    try:
        import torch  # noqa: F401 — probe only; bench_torch_cpu imports
        import transformers  # noqa: F401

        baseline_fn = bench_torch_cpu
        metric = "gpt2_fwd_tokens_per_sec_per_chip_vs_torch_cpu"
    except Exception:
        baseline_fn = bench_jax_cpu
        metric = "gpt2_fwd_tokens_per_sec_per_chip_vs_jax_cpu"
    # A-B-A-B interleave, median of >= 3 pairs (VERDICT r5 weak #3): the
    # ratio previously paired ONE repo measurement with ONE baseline
    # measurement taken after it, so host-load drift between the two
    # swung the headline ~15% round-over-round. Interleaving puts both
    # legs under the same load regime and the per-pair ratios expose the
    # remaining noise as an explicit spread instead of silent drift.
    pairs = []
    while len(pairs) < 3:
        a = bench_ours(light=on_cpu)
        try:
            b = baseline_fn()
        except Exception:
            if baseline_fn is bench_jax_cpu:
                raise  # no further fallback
            # torch present but broke mid-run: switch baselines AND
            # discard earlier pairs — a median over mixed torch/jax
            # denominators under one metric key is exactly the
            # cross-substrate comparison the key exists to prevent
            baseline_fn = bench_jax_cpu
            metric = "gpt2_fwd_tokens_per_sec_per_chip_vs_jax_cpu"
            pairs = []
            continue
        pairs.append((a, b))
    ratios = sorted(a / b for a, b in pairs)
    ours = sorted(a for a, _ in pairs)[len(pairs) // 2]
    vs_baseline = ratios[len(ratios) // 2]
    if on_cpu:
        # distinct key: a CPU-substrate number must never be compared
        # against TPU rounds under the headline metric name — whether we
        # landed here via the wedge fallback or a TPU-less host
        metric = metric.replace("per_chip", "cpu_fallback")
    row = {
        "metric": metric,
        "value": round(ours, 1),
        "unit": "tokens/s",
        "vs_baseline": round(vs_baseline, 2),
        # spread of the interleaved per-pair ratios (max - min): the
        # uncertainty the single-shot ratio used to hide
        "vs_baseline_spread": round(ratios[-1] - ratios[0], 3),
        "vs_baseline_pairs": [round(r, 3) for r in ratios],
    }
    prev = _previous_round_ratio()
    if prev is not None:
        row["vs_baseline_prev_round"] = prev
    # MFU: the round-over-round "fast on TPU" number (vs_baseline only says
    # "faster than the reference's CPU substrate"). Omitted off-TPU.
    from dnn_tpu.models import gpt
    from dnn_tpu.utils.flops import gpt_forward_flops, mfu

    cfg = gpt.PRESETS["gpt2"]
    m = mfu(gpt_forward_flops(cfg, BATCH, SEQ) / (BATCH * SEQ), ours)
    if m is not None:
        row["mfu"] = round(m, 4)
    row["platform"] = jax.default_backend()
    # provenance (ISSUE 8): round_substrate is the contract-named alias
    # of platform (the substrate the round ACTUALLY ran on), plus
    # whether the chip recovered via the supervisor restart path — a
    # recovered chip yields an on-chip row, never a silent CPU row
    row["round_substrate"] = row["platform"]
    from dnn_tpu import obs as _obs_prov

    if _obs_prov.flight.recorder().events(kind="probe_recovered"):
        row["probe_recovered"] = True
    if fell_back:
        row["note"] = "default backend unresponsive; CPU fallback"
    # live decode goodput (ISSUE 6): every round's row carries the
    # serving hot path's dnn_tpu_mfu / dnn_tpu_mbu gauges, measured
    # fresh on this round's substrate (benchmarks/decode_mbu_probe.py,
    # light leg) — the MBU-gap trend rides BENCH_r*.json automatically,
    # like stale_tpu_reference already does. Never allowed to cost the
    # round its headline: any failure lands as a labeled error field.
    try:
        from benchmarks.decode_mbu_probe import measure as _mbu_measure

        g = _mbu_measure(light=True)
        row["decode_goodput"] = {
            k: g[k] for k in ("mfu", "mbu", "tokens_per_sec",
                              "rooflines", "platform", "asserted_leg",
                              "vs_studies_s10")}
    except Exception as e:  # noqa: BLE001 — headline must survive
        row["decode_goodput"] = {"error": str(e)[:200]}
    # inter-stage transport contract (ISSUE 7): every round's row carries
    # the relay_transport A-B numbers — negotiated-auto vs nested-grpc
    # per-hop p50 and the fleet-stitched bubble fraction, measured fresh
    # on real stage subprocesses (benchmarks/relay_transport_probe.py,
    # light leg). Error-isolated like decode_goodput: never allowed to
    # cost the round its headline.
    try:
        from benchmarks.relay_transport_probe import measure as _rt_measure

        r = _rt_measure(light=True)
        row["relay_transport"] = {
            "hop_p50_ratio": r["hop_p50_ratio"],
            "bubble_drop": r["bubble_drop"],
            "vs_studies_s10": r["vs_studies_s10"],
            "negotiated": r["auto"]["negotiated"],
            "hop_nested_grpc_p50_ms": r["grpc"]["hop_nested_p50_ms"],
            "hop_streamed_auto_p50_ms": r["auto"]["hop_streamed_p50_ms"],
            "ok": r["ok"],
        }
    except Exception as e:  # noqa: BLE001 — headline must survive
        row["relay_transport"] = {"error": str(e)[:200]}
    from dnn_tpu import obs

    if on_cpu:
        # a CPU-substrate round still surfaces the last committed on-chip
        # headline (distinctly labeled) so no round ships perf-blind
        ref = _last_good_tpu_reference()
        if ref is not None:
            row["stale_tpu_reference"] = ref
            m = obs.metrics()
            if m is not None:
                m.inc("bench.stale_tpu_reference_used_total")
            obs.flight.record("stale_tpu_reference", commit=ref["commit"],
                              date=ref["date"], value=ref["value"])
    # the probe/echo outcomes as EVENTS on the row whatever substrate the
    # round landed on — a TPU round that recovered after a transient
    # probe failure must still ship the flap machine-readably (the
    # free-text `note` stays for humans): the round driver can count
    # probe_fail/probe_recovered/stale_tpu_reference without parsing prose
    events = obs.flight.recorder().events()
    outcomes = [e for e in events
                if e["kind"] in ("probe_fail", "probe_exhausted",
                                 "probe_recovered",
                                 "stale_tpu_reference")]
    if outcomes:
        row["flight_events"] = outcomes
    rc = 0
    if require is not None:
        # the substrate contract decides the row's ok — a CPU-fallback
        # round against --require-substrate tpu is a FAILED row (and a
        # nonzero exit), never a silently-mislabeled data point
        row["required_substrate"] = require
        row["ok"] = row["round_substrate"] == require
        if not row["ok"]:
            rc = 1
            row["note"] = (row.get("note", "") + "; " if row.get("note")
                           else "") + (
                f"required substrate '{require}' but the round ran on "
                f"'{row['round_substrate']}'")
    print(json.dumps(row), flush=True)
    if not on_cpu:
        # headline is safely out; now spend the healthy chip on the full
        # canonical table (resume semantics — only missing/failed configs)
        _refresh_results_table()
    return rc


if __name__ == "__main__":
    import sys

    sys.exit(main())
